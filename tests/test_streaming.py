"""Streaming data plane tests (bert_pytorch_tpu/data/streaming.py).

The contract under test, per docs/DATA.md: the streaming plane's batch
stream is a pure function of (sources, seed, epoch, cursor) — masks
included — so resume from a checkpointed cursor is BIT-identical to an
unbroken run (the offline loader only promises rng-independent fields);
multi-host record sharding is disjoint and deterministic; the fault drills
(corrupt record / worker crash / slow producer) degrade loudly and
deterministically; and the run_pretraining `--stream_dir` sub-mode feeds
the identical train loop (flight recorder, replay, /metrics included).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.data.streaming import (  # noqa: E402
    INJECT_SLOW_SLEEP_S,
    StreamingPretrainingLoader,
    discover_sources,
)
from bert_pytorch_tpu.data.tokenization import (  # noqa: E402
    BertWordPieceTokenizer)
from bert_pytorch_tpu.telemetry.registry import (  # noqa: E402
    MetricsRegistry, parse_prometheus)

HERE = os.path.dirname(os.path.abspath(__file__))

WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
         "hotel", "india", "juliet", "kilo", "lima", "mike", "november",
         "oscar", "papa"]
SPECIALS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
VOCAB = {t: i for i, t in enumerate(SPECIALS + WORDS)}
MASK_ID = VOCAB["[MASK]"]


def write_corpus(dirpath, n_docs=20, seed=0, n_files=2):
    """Blank-line-delimited documents of random word sentences."""
    rng = np.random.RandomState(seed)
    os.makedirs(dirpath, exist_ok=True)
    for f in range(n_files):
        lines = []
        for _ in range(n_docs // n_files):
            for _ in range(rng.randint(2, 6)):
                lines.append(" ".join(rng.choice(WORDS, rng.randint(3, 12))))
            lines.append("")
        with open(os.path.join(dirpath, f"c{f}.txt"), "w",
                  encoding="utf-8") as fh:
            fh.write("\n".join(lines))
    return dirpath


def write_vocab(path):
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(SPECIALS + WORDS) + "\n")
    return str(path)


def make_loader(corpus_dir, batch_size=4, seq_len=16, prefetch=0,
                packing=False, world_size=1, rank=0, inject=None,
                registry=None, num_workers=2, seed=7, **kw):
    return StreamingPretrainingLoader(
        discover_sources(str(corpus_dir)), BertWordPieceTokenizer(VOCAB),
        batch_size=batch_size, seq_len=seq_len, mask_token_index=MASK_ID,
        max_pred_per_seq=3, masked_lm_prob=0.15, vocab_size=len(VOCAB),
        seed=seed, world_size=world_size, rank=rank,
        num_workers=num_workers, prefetch_batches=prefetch,
        packing=packing, packing_max_segments=4, packing_lookahead=2,
        registry=registry, inject=inject, **kw)


def originals(batch):
    """Undo masking via the labels — the mask-independent token stream."""
    return np.where(batch["masked_lm_labels"] != -1,
                    batch["masked_lm_labels"], batch["input_ids"])


def assert_streams_equal(a, b, start=0):
    assert len(a) - start == len(b), (len(a), start, len(b))
    for want, got in zip(a[start:], b):
        assert set(want) == set(got)
        for k in want:
            np.testing.assert_array_equal(want[k], got[k], err_msg=k)


# -- batch contract -----------------------------------------------------------

def test_stream_batch_contract(tmp_path):
    """The yielded dict is the train loop's pretraining contract: same
    keys/shapes/dtypes as the offline loader, masking applied, single
    segment (type ids 0), NSP label 0."""
    write_corpus(tmp_path / "c")
    reg = MetricsRegistry()
    lo = make_loader(tmp_path / "c", registry=reg)
    batches = list(lo)
    lo.close()
    assert len(batches) >= 2
    for b in batches:
        assert b["input_ids"].shape == (4, 16)
        assert b["input_ids"].dtype == np.int32
        assert b["masked_lm_labels"].shape == (4, 16)
        assert b["next_sentence_labels"].shape == (4,)
        assert (b["token_type_ids"] == 0).all()
        assert (b["next_sentence_labels"] == 0).all()
        assert (b["masked_lm_labels"] != -1).sum() > 0
        # every row frames [CLS] ... [SEP], pad tail zero
        assert (b["input_ids"][:, 0] == VOCAB["[CLS]"]).all()
        assert ((b["attention_mask"] == 1) | (b["input_ids"] == 0)).all()
    parsed = parse_prometheus(reg.render_prometheus())
    assert parsed["bert_stream_tokens_total"][""] > 0
    assert parsed["bert_stream_records_total"][""] > 0
    assert parsed["bert_stream_records_dropped_total"][""] == 0
    assert "bert_stream_queue_depth" in parsed
    assert any(k.startswith("bert_stream_worker_tokens_per_sec")
               for k in parsed)


def test_stream_prefetch_and_workers_change_pacing_only(tmp_path):
    """Assembly prefetch depth and tokenize worker count must not move a
    single bit of the stream — results are consumed in submission order."""
    write_corpus(tmp_path / "c")
    base = make_loader(tmp_path / "c", prefetch=0, num_workers=1)
    sync = list(base)
    base.close()
    for prefetch, workers in ((3, 2), (1, 4)):
        lo = make_loader(tmp_path / "c", prefetch=prefetch,
                         num_workers=workers)
        assert_streams_equal(sync, list(lo))
        lo.close()


# -- resume determinism (satellite) -------------------------------------------

def test_stream_resume_bit_identical_packed_prefetch(tmp_path):
    """THE streaming analog of test_packed_loader_resume_determinism, but
    stronger: kill mid-epoch with packing + prefetch on, resume from the
    checkpointed cursor, and the resumed stream is bit-identical INCLUDING
    the masks (the rng is a pure function of the cursor)."""
    write_corpus(tmp_path / "c", n_docs=24)
    unbroken = make_loader(tmp_path / "c", prefetch=2, packing=True)
    full = list(unbroken)
    unbroken.close()
    assert len(full) >= 4
    # rows genuinely packed
    assert max(int(b["segment_ids"].max()) for b in full) >= 2

    first = make_loader(tmp_path / "c", prefetch=2, packing=True)
    it = iter(first)
    next(it)
    next(it)
    state = first.state_dict()
    first.close()
    assert state["stream"] == 1 and state["pending"], state

    resumed = make_loader(tmp_path / "c", prefetch=2, packing=True)
    resumed.load_state_dict(state)
    assert_streams_equal(full, list(resumed), start=2)
    resumed.close()


def test_stream_resume_bit_identical_unpacked(tmp_path):
    write_corpus(tmp_path / "c")
    unbroken = make_loader(tmp_path / "c", prefetch=2)
    full = list(unbroken)
    unbroken.close()
    part = make_loader(tmp_path / "c", prefetch=2)
    next(iter(part))
    state = part.state_dict()
    part.close()
    resumed = make_loader(tmp_path / "c")  # prefetch off on resume: same bits
    resumed.load_state_dict(state)
    assert_streams_equal(full, list(resumed), start=1)
    resumed.close()


def test_stream_epoch_pass_remasks_same_data(tmp_path):
    """Online RoBERTa dynamic masking per epoch-pass: the token stream is
    identical across epochs, the masks are not."""
    write_corpus(tmp_path / "c")
    lo = make_loader(tmp_path / "c")
    e0 = list(lo)
    lo.reset_epoch()
    e1 = list(lo)
    lo.close()
    assert len(e0) == len(e1) >= 2
    assert all((originals(a) == originals(b)).all()
               for a, b in zip(e0, e1))
    assert any((a["input_ids"] != b["input_ids"]).any()
               for a, b in zip(e0, e1))


def test_stream_bpe_convention_tokens_accepted(tmp_path):
    """The loader accepts RoBERTa-style <s>/</s>/<mask> specials (the
    repo's BPE trainer's convention) as well as the BERT names — the
    --stream_tokenizer bpe path must not require [CLS]."""
    from bert_pytorch_tpu.data.streaming import resolve_mask_id

    class StubBPE:
        """Duck-typed tokenizer: <s>/</s>/<mask> specials, word -> id."""

        vocab = {t: i for i, t in enumerate(
            ["<pad>", "<unk>", "<s>", "</s>", "<mask>"] + WORDS)}

        def token_to_id(self, tok):
            return self.vocab.get(tok)

        def encode(self, text, add_special_tokens=True):
            class Enc:
                pass

            enc = Enc()
            enc.ids = [self.vocab.get(w, 1) for w in text.split()]
            return enc

    write_corpus(tmp_path / "c")
    tok = StubBPE()
    assert resolve_mask_id(tok) == 4
    lo = StreamingPretrainingLoader(
        discover_sources(str(tmp_path / "c")), tok, batch_size=4,
        seq_len=16, mask_token_index=4, max_pred_per_seq=3,
        masked_lm_prob=0.15, vocab_size=len(tok.vocab), seed=7)
    b = next(iter(lo))
    assert (b["input_ids"][:, 0] == tok.vocab["<s>"]).all()
    assert (b["masked_lm_labels"] != -1).sum() > 0
    lo.close()


def test_stream_resume_vanished_pending_fails_loudly(tmp_path):
    """A checkpointed pending example that never comes back on resume
    (corpus/injection drift the hash cannot see) must raise a loud error
    naming the cursor, not die opaquely inside np.stack."""
    write_corpus(tmp_path / "c", n_docs=24)
    lo = make_loader(tmp_path / "c", packing=True)
    next(iter(lo))
    state = lo.state_dict()
    lo.close()
    assert state["pending"]
    # point one pending meta at an example index its record never yields
    state["pending"][0] = [0, 0, 0, 57]
    res = make_loader(tmp_path / "c", packing=True)
    res.load_state_dict(state)
    with pytest.raises(RuntimeError, match="vanished"):
        list(res)
    res.close()


def test_stream_state_refused_on_corpus_change(tmp_path):
    """A cursor indexes one source enumeration and no other: restoring
    against a changed corpus (or an offline-plane sampler state) warns and
    starts fresh instead of silently misreading records."""
    write_corpus(tmp_path / "c")
    lo = make_loader(tmp_path / "c", packing=True)
    next(iter(lo))
    state = lo.state_dict()
    lo.close()

    write_corpus(tmp_path / "other", n_docs=30, seed=9)
    other = make_loader(tmp_path / "other", packing=True)
    with pytest.warns(UserWarning, match="source list changed"):
        other.load_state_dict(state)
    assert other._pending == [] and other._cursor == (0, 0, 0, 0)
    other.close()

    # an in-place edit that keeps the byte length still changes the
    # fingerprint (mtime is hashed): same-length corpus drift refuses too
    victim = tmp_path / "c" / "c0.txt"
    orig = os.stat(victim)
    os.utime(victim, ns=(1, 1))
    touched = make_loader(tmp_path / "c", packing=True)
    with pytest.warns(UserWarning, match="source list changed"):
        touched.load_state_dict(state)
    touched.close()
    os.utime(victim, ns=(orig.st_atime_ns, orig.st_mtime_ns))

    same = make_loader(tmp_path / "c", packing=True)
    with pytest.warns(UserWarning, match="not a streaming-plane state"):
        same.load_state_dict({"epoch": 0, "index": 8, "total_size": 40})
    same.close()

    # a different seed would silently break mask bit-identity mid-stream
    reseeded = make_loader(tmp_path / "c", packing=True, seed=8)
    with pytest.warns(UserWarning, match="seed changed"):
        reseeded.load_state_dict(state)
    assert reseeded._cursor == (0, 0, 0, 0)
    reseeded.close()

    # a packed checkpoint's pending examples have nowhere to go unpacked
    assert state["pending"]
    unpacked = make_loader(tmp_path / "c", packing=False)
    with pytest.warns(UserWarning, match="packing is off"):
        unpacked.load_state_dict(state)
    assert unpacked._cursor == (0, 0, 0, 0) and not unpacked._pending
    unpacked.close()


# -- multi-host sharding (satellite) ------------------------------------------

def doc_words(i, n=3):
    """Encode doc index i as two leading word tokens (base len(WORDS)) so
    batch content names its source record; pad to a short sentence."""
    hi, lo = divmod(i, len(WORDS))
    extra = [WORDS[(i + k) % len(WORDS)] for k in range(n)]
    return " ".join([WORDS[hi], WORDS[lo]] + extra)


def test_stream_two_process_disjoint_deterministic_shards(tmp_path):
    """Two real OS processes, ranks 0/1 of world 2, over one corpus whose
    documents self-identify in their token stream: the consumed record
    sets are disjoint, cover the corpus (minus the dropped tail), and a
    rank's stream is deterministic across runs."""
    corpus = tmp_path / "c"
    os.makedirs(corpus)
    n_docs = 30
    # one short single-sentence doc per record: every record = one example,
    # so rows decode unambiguously to their doc index
    docs = [doc_words(i) for i in range(n_docs)]
    (corpus / "a.txt").write_text("\n\n".join(docs[:15]) + "\n")
    (corpus / "b.txt").write_text("\n\n".join(docs[15:]) + "\n")
    vocab = write_vocab(tmp_path / "vocab.txt")

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep \
        + env.get("PYTHONPATH", "")

    def run(rank, tag):
        out = str(tmp_path / f"out_{tag}.json")
        subprocess.run(
            [sys.executable, os.path.join(HERE, "stream_shard_child.py"),
             str(corpus), vocab, str(rank), "2", out],
            env=env, check=True, timeout=120)
        with open(out, encoding="utf-8") as f:
            return json.load(f)

    r0, r1, r0_again = run(0, "r0"), run(1, "r1"), run(0, "r0b")
    assert r0["docs"] and r1["docs"]
    assert not set(r0["docs"]) & set(r1["docs"]), "shards overlap"
    # full coverage minus at most one dropped tail batch per rank
    covered = set(r0["docs"]) | set(r1["docs"])
    assert len(covered) >= n_docs - 2 * 4
    # even/odd global enumeration: ownership is the documented contract
    assert all(d % 2 == 0 for d in r0["docs"])
    assert all(d % 2 == 1 for d in r1["docs"])
    assert r0 == r0_again, "rank stream not deterministic across runs"


# -- fault-injection drills (satellite) ---------------------------------------

def test_stream_inject_corrupt_record_skipped_and_counted(tmp_path):
    write_corpus(tmp_path / "c")
    reg = MetricsRegistry()
    import warnings as W

    with W.catch_warnings(record=True) as caught:
        W.simplefilter("always")
        lo = make_loader(tmp_path / "c", inject="corrupt_record",
                         registry=reg)
        s1 = list(lo)
        lo.close()
    dropped = reg.counter("bert_stream_records_dropped_total").value()
    assert dropped >= 1
    assert any("DROPPING corrupt record" in str(w.message) for w in caught)
    assert s1, "stream must survive corrupt records"
    # the drop is deterministic: a second injected run streams identically
    lo2 = make_loader(tmp_path / "c", inject="corrupt_record")
    assert_streams_equal(s1, list(lo2))
    lo2.close()


def test_stream_inject_worker_crash_restarts_with_cursor_intact(tmp_path):
    """A dead tokenize task is detected, counted, and re-submitted with
    its cursor intact — the surviving stream is bit-identical to an
    uninjected run (nothing skipped, nothing repeated)."""
    write_corpus(tmp_path / "c")
    clean = make_loader(tmp_path / "c")
    want = list(clean)
    clean.close()
    reg = MetricsRegistry()
    lo = make_loader(tmp_path / "c", inject="worker_crash", registry=reg)
    assert_streams_equal(want, list(lo))
    lo.close()
    assert reg.counter("bert_stream_worker_restarts_total").value() >= 1
    assert reg.counter("bert_stream_records_dropped_total").value() == 0


def test_stream_inject_slow_producer_surfaces_as_data_wait(tmp_path):
    """A stalled producer starves the consumer, and the consumer's blocked
    time is exactly what the train loop bills to the data_wait StepWatch
    bucket — assert it dominates the paced loop."""
    from bert_pytorch_tpu.telemetry.stepwatch import StepWatch

    write_corpus(tmp_path / "c")
    lo = make_loader(tmp_path / "c", inject="slow_producer", num_workers=1)
    sw = StepWatch(flops_per_step=1.0, seqs_per_step=4, seq_len=16,
                   peak_flops=None, log_freq=10 ** 6)
    t0 = time.perf_counter()
    it = iter(lo)
    while True:
        with sw.phase("data_wait"):
            try:
                next(it)
            except StopIteration:
                break
        sw.step_done()
    total = time.perf_counter() - t0
    lo.close()
    rec = sw.flush()
    assert rec is not None
    wait_frac = rec["data_wait_ms"] * rec["steps"] / (total * 1e3)
    assert wait_frac > 0.5, (rec, total)
    # the injected per-record sleep is a hard lower bound on wall time
    n_records = 20  # write_corpus default docs
    assert total >= INJECT_SLOW_SLEEP_S * n_records * 0.5


# -- CLI validation (satellite) -----------------------------------------------

def test_stream_cli_validation(tmp_path):
    import run_pretraining

    # the two planes conflict loudly at argparse time
    with pytest.raises(SystemExit):
        run_pretraining.parse_arguments(
            ["--input_dir", "/x", "--stream_dir", "/y"])
    # stream-dependent flags without the plane selected — detected by
    # explicit presence, so even passing the DEFAULT value conflicts
    with pytest.raises(SystemExit):
        run_pretraining.parse_arguments(
            ["--input_dir", "/x", "--stream_workers", "8"])
    with pytest.raises(SystemExit):
        run_pretraining.parse_arguments(
            ["--input_dir", "/x", "--stream_workers", "2"])
    with pytest.raises(SystemExit):
        run_pretraining.parse_arguments(["--stream_inject", "worker_crash"])
    # a shared run-config JSON may carry stream keys for streaming jobs;
    # an offline run tolerates (ignores) them — only CLI flags conflict
    cfg = tmp_path / "run.json"
    cfg.write_text(json.dumps({"stream_seq_len": 64, "stream_workers": 4}))
    offline_cfg = run_pretraining.parse_arguments(
        ["--config_file", str(cfg), "--input_dir", "/x"])
    assert offline_cfg.stream_seq_len == 64  # config landed, unused
    # ...and an offline run must NOT read a config-sourced stream_vocab
    # for its [MASK] id (the shards were encoded with a different vocab)
    vocab = write_vocab(tmp_path / "alt_vocab.txt")
    cfg.write_text(json.dumps({"stream_vocab": vocab}))
    offline_cfg = run_pretraining.parse_arguments(
        ["--config_file", str(cfg), "--input_dir", "/x"])

    class NoVocabCfg:
        vocab_file = None

    assert run_pretraining.find_mask_token_index(
        offline_cfg, NoVocabCfg()) == 103  # standard default, not 4
    stream_cfg = run_pretraining.parse_arguments(
        ["--config_file", str(cfg), "--stream_dir", "/y"])
    assert run_pretraining.find_mask_token_index(
        stream_cfg, NoVocabCfg()) == 4  # stream mode DOES read it
    # an explicit CLI plane choice beats a config-sourced one
    cfg.write_text(json.dumps({"input_dir": "/from_config"}))
    chose_stream = run_pretraining.parse_arguments(
        ["--config_file", str(cfg), "--stream_dir", "/y"])
    assert chose_stream.stream_dir == "/y"
    assert chose_stream.input_dir is None
    cfg.write_text(json.dumps({"stream_dir": "/from_config"}))
    chose_offline = run_pretraining.parse_arguments(
        ["--config_file", str(cfg), "--input_dir", "/x"])
    assert chose_offline.input_dir == "/x"
    assert chose_offline.stream_dir is None
    # a fully-configured stream mode parses, h2d default intact (the
    # staging path is shared, so the default must be identical)
    args = run_pretraining.parse_arguments(
        ["--stream_dir", "/y", "--stream_workers", "8",
         "--stream_seq_len", "64"])
    assert args.stream_workers == 8
    assert args.h2d_prefetch == 1
    offline = run_pretraining.parse_arguments(["--input_dir", "/x"])
    assert offline.h2d_prefetch == args.h2d_prefetch


# -- manifest schema (satellite) ----------------------------------------------

def test_stream_manifest_key_validation():
    from bert_pytorch_tpu.telemetry.flight_recorder import validate_manifest

    base = {
        "schema_version": 2, "reason": "nonfinite", "trigger_step": 3,
        "created_unix": 0.0, "provenance": {},
        "model_config": {"hidden_size": 32, "num_hidden_layers": 2},
        "run": {k: 0 for k in (
            "accum_steps", "steps_per_loop", "seed", "max_pred_row",
            "grad_dtype", "optimizer", "learning_rate", "lr_decay",
            "warmup_proportion", "max_steps", "previous_phase_end_step",
            "rng_impl", "health_pack", "nonfinite_action", "zero1", "mesh",
            "seq_len", "packing")},
        "checkpoint": {}, "records": [
            {"step": 3, "pos": 0, "n_steps": 1, "fields": []}],
        "metrics_tail": [], "metrics_tail_source": None, "registry": {},
    }
    assert validate_manifest(dict(base)) == []
    assert validate_manifest(dict(base, stream=None)) == []
    good_stream = {"sources_hash": "ab12", "sources": ["a.txt"],
                   "source_offsets": [3], "cursor": {"epoch": 0},
                   "recent_batches": [
                       {"batch": 1, "record_lo": 0, "record_hi": 4}]}
    assert validate_manifest(dict(base, stream=good_stream)) == []
    errs = validate_manifest(dict(base, stream={"cursor": {}}))
    assert errs and "stream" in errs[0]
    errs = validate_manifest(dict(
        base, stream=dict(good_stream, recent_batches=[{"batch": 1}])))
    assert errs and "recent_batches" in errs[0]
    # non-list recent_batches must report INVALID, not TypeError
    errs = validate_manifest(dict(
        base, stream=dict(good_stream, recent_batches=5)))
    assert errs and "stream" in errs[0]


# -- entry-point e2e ----------------------------------------------------------

def _model_cfg(tmp_path):
    cfg = {
        "vocab_size": len(VOCAB), "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "tokenizer": "wordpiece", "fused_ops": False,
        "attention_impl": "xla",
    }
    path = tmp_path / "model_config.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def _stream_argv(tmp_path, out, extra=()):
    data = tmp_path / "corpus"
    if not data.exists():
        write_corpus(data, n_docs=80, seed=0)
    vocab = tmp_path / "vocab.txt"
    if not vocab.exists():
        write_vocab(vocab)
    return ["--model_config_file", _model_cfg(tmp_path),
            "--stream_dir", str(data), "--stream_vocab", str(vocab),
            "--stream_seq_len", "32", "--output_dir", str(out),
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--learning_rate", "1e-3", "--global_batch_size", "32",
            "--local_batch_size", "2", "--max_predictions_per_seq", "5",
            "--log_freq", "1", "--log_prefix", "testlog"] + list(extra)


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_stream_entrypoint_nan_inject_bundle_replay_resume(tmp_path):
    """Acceptance: a streaming-mode run (packing on) with an injected NaN
    dumps a repro bundle whose manifest carries the stream cursor, the
    bundle replays BIT-identically via tools/replay.py, --validate
    type-checks the stream key (and loud-fails when it is corrupted), and
    the run auto-resumes from the checkpointed stream cursor."""
    import run_pretraining
    import tools.replay as replay

    out = tmp_path / "out"
    argv = _stream_argv(tmp_path, out, extra=[
        "--packing", "--packing_max_segments", "4", "--max_steps", "3",
        "--num_steps_per_checkpoint", "2", "--inject_nonfinite_step", "3"])
    final, _ = run_pretraining.main(argv)
    assert final == 3
    log = (out / "testlog.txt").read_text()
    assert "STREAMING" in log
    # --h2d_prefetch default applies identically in stream mode
    assert "h2d prefetch: depth 1" in log
    assert "NON-FINITE" in log

    bundle = os.path.join(out, "repro_bundles", "step00000003_nonfinite")
    assert os.path.isdir(bundle), os.listdir(
        os.path.join(out, "repro_bundles"))
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    stream = manifest["stream"]
    assert stream["sources_hash"] and len(stream["sources"]) == 2
    assert manifest["run"]["stream"] is True
    assert isinstance(stream["cursor"]["global_seq"], int)
    assert stream["recent_batches"], "batch->record windows missing"

    assert replay._cli(["--bundle", bundle, "--validate"]) == 0
    result = replay.main(["--bundle", bundle])
    assert result["match"] is True, result["mismatches"]

    # corrupt the stream key -> --validate loud-fails
    broken = dict(manifest, stream={"cursor": {}})
    bpath = tmp_path / "broken"
    bpath.mkdir()
    (bpath / "manifest.json").write_text(json.dumps(broken))
    import shutil

    shutil.copy(os.path.join(bundle, "batches.npz"),
                bpath / "batches.npz")
    assert replay._cli(["--bundle", str(bpath), "--validate"]) == 2

    # resume: the stream cursor restores from the checkpoint
    final2, _ = run_pretraining.main(
        argv[:-4] + ["--num_steps_per_checkpoint", "2", "--max_steps", "4"])
    assert final2 == 4
    assert "auto-resumed from step 3" in (out / "testlog.txt").read_text()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def test_stream_entrypoint_live_metrics_with_corrupt_records(tmp_path):
    """Acceptance: scrape /metrics WHILE a streaming run (with the
    corrupt_record drill active) trains — queue-depth / tokens /
    dropped-records gauges export live alongside the step counter."""
    import run_pretraining

    port = _free_port()
    out = tmp_path / "out"
    argv = _stream_argv(tmp_path, out, extra=[
        "--max_steps", "30", "--skip_checkpoint", "--flight_recorder",
        "off", "--metrics_port", str(port),
        "--stream_inject", "corrupt_record"])

    result = {}

    def run():
        try:
            result["final"] = run_pretraining.main(argv)
        except BaseException as e:
            result["exc"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    lab = '{phase="pretrain"}'
    caught = None
    deadline = time.time() + 300
    while time.time() < deadline and (t.is_alive() or caught is None):
        try:
            parsed = parse_prometheus(_get(
                f"http://127.0.0.1:{port}/metrics", timeout=2))
            hz = json.loads(_get(f"http://127.0.0.1:{port}/healthz",
                                 timeout=2))
        except Exception:
            time.sleep(0.02)
            continue
        steps = parsed.get("bert_train_steps_total", {}).get(lab, 0)
        dropped = parsed.get("bert_stream_records_dropped_total",
                             {}).get(lab, 0)
        if steps >= 2 and dropped >= 1:
            caught = (parsed, hz)
            break
        time.sleep(0.02)
    t.join(timeout=300)
    assert "exc" not in result, result.get("exc")
    assert caught is not None, f"no live scrape caught (run: {result})"
    parsed, hz = caught
    assert parsed["bert_stream_tokens_total"][lab] > 0
    assert parsed["bert_stream_records_total"][lab] > 0
    assert parsed["bert_stream_records_dropped_total"][lab] >= 1
    assert lab in parsed["bert_stream_queue_depth"]
    assert any(k == "bert_stream_worker_tokens_per_sec"
               for k in parsed)
    # /healthz names the plane's live cursor (telemetry/run.py
    # attach_stream)
    assert hz["stream"]["sources_hash"]
    assert "global_seq" in hz["stream"] and "pending" not in hz["stream"]
    assert result.get("final", (0,))[0] == 30
