"""Sequence-packing tests: the first-fit packer, segment-aware flash
attention parity (both kernel layouts + both backwards + the XLA fallback)
against a block-diagonal dense reference, the bit-exact no-cross-
contamination contract, packed-vs-unpacked loss equality, and StepWatch's
real-token accounting."""

import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bert_pytorch_tpu.data import packing as P

SEQ = 32


# -- first-fit packer -------------------------------------------------------

def test_first_fit_hand_checked_layout():
    # capacity 10, 3 bins: first-fit in arrival order, no sorting
    bins = P.first_fit([6, 5, 4, 3, 2, 9], n_bins=3, capacity=10,
                       max_segments=4)
    # 6->bin0; 5->bin1; 4->bin0 (6+4=10); 3->bin1 (5+3=8); 2->bin1 (10);
    # 9->bin2
    assert bins == [[0, 2], [1, 3, 4], [5]]


def test_first_fit_respects_max_segments():
    bins = P.first_fit([1, 1, 1, 1], n_bins=2, capacity=10, max_segments=2)
    assert bins == [[0, 1], [2, 3]]


def test_first_fit_oversize_raises():
    with pytest.raises(ValueError):
        P.first_fit([11], n_bins=1, capacity=10, max_segments=2)


def test_first_fit_unplaceable_examples_left_out():
    bins = P.first_fit([10, 10, 10], n_bins=2, capacity=10, max_segments=2)
    assert bins == [[0], [1]]  # example 2 fits nowhere — stays pending


def _example_batch(lens, seed=0):
    rng = np.random.RandomState(seed)
    n = len(lens)
    ids = np.zeros((n, SEQ), np.int32)
    tok = np.zeros((n, SEQ), np.int32)
    am = np.zeros((n, SEQ), np.int32)
    lab = np.full((n, SEQ), -1, np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rng.randint(5, 64, L)
        ids[i, 0] = 1
        ids[i, L - 1] = 2
        tok[i, L // 2:L] = 1
        am[i, :L] = 1
        mpos = rng.choice(np.arange(1, L - 1), 2, replace=False)
        lab[i, mpos] = ids[i, mpos]
        ids[i, mpos] = 3
    return {"input_ids": ids, "token_type_ids": tok, "attention_mask": am,
            "masked_lm_labels": lab,
            "next_sentence_labels": rng.randint(0, 2, (n,)).astype(np.int32)}


def test_pack_examples_fields():
    lens = [10, 14, 8, 20]
    ex = _example_batch(lens)
    bins = P.first_fit(P.example_lengths(ex["attention_mask"]), 2, SEQ, 4)
    out = P.pack_examples(ex, bins, SEQ, 4)
    assert out["input_ids"].shape == (2, SEQ)
    assert out["next_sentence_labels"].shape == (2, 4)
    for b, members in enumerate(bins):
        seg = out["segment_ids"][b]
        assert int((seg > 0).sum()) == sum(lens[i] for i in members)
        np.testing.assert_array_equal(out["attention_mask"][b], seg > 0)
        for g, ei in enumerate(members):
            idxs = np.nonzero(seg == g + 1)[0]
            L = lens[ei]
            assert len(idxs) == L and (np.diff(idxs) == 1).all()
            # tokens, token types and labels ride across verbatim
            np.testing.assert_array_equal(out["input_ids"][b, idxs],
                                          ex["input_ids"][ei, :L])
            np.testing.assert_array_equal(out["token_type_ids"][b, idxs],
                                          ex["token_type_ids"][ei, :L])
            np.testing.assert_array_equal(out["masked_lm_labels"][b, idxs],
                                          ex["masked_lm_labels"][ei, :L])
            # per-segment position reset + NSP slot
            np.testing.assert_array_equal(out["position_ids"][b, idxs],
                                          np.arange(L))
            assert out["nsp_positions"][b, g] == idxs[0]
            assert (out["next_sentence_labels"][b, g]
                    == ex["next_sentence_labels"][ei])
        # empty slots carry the -1 ignore label
        assert (out["next_sentence_labels"][b, len(members):] == -1).all()


# -- segment-aware flash attention ------------------------------------------

def _packed_qkv(b=2, s=256, h=2, d=64, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.array(rng.randn(b, s, h, d).astype(np.float32)) * 0.5
    seg = np.zeros((b, s), np.int32)
    # segments deliberately spanning the 128-wide tile boundaries
    seg[0, :100] = 1
    seg[0, 100:180] = 2
    seg[0, 180:230] = 3
    seg[1, :60] = 1
    seg[1, 60:200] = 2  # row 1 has a pad tail from 200
    return mk(), mk(), mk(), jnp.array(seg)


def _dense_block_diag(q, k, v, seg):
    """Dense reference: additive block-diagonal mask, fp32 softmax — the
    exact mirror of the in-kernel masking (same -1e30 constant)."""
    from bert_pytorch_tpu.ops.attention import make_segment_attention_bias

    d = q.shape[-1]
    sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                    k.astype(jnp.float32)) / jnp.sqrt(d)
    sc = sc + make_segment_attention_bias(seg)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("layout,bwd,skip", [
    ("native", "fused", "1"),
    ("native", "fused", "0"),
    ("bh", "fused", "1"),
    ("bh", "split", "1"),
])
def test_flash_segments_match_dense_reference(layout, bwd, skip,
                                              monkeypatch):
    """Packed forward/backward vs the block-diagonal dense reference, on
    every kernel path: native + bh layouts, fused + split backwards, block
    skipping on and off. 128-wide blocks force multi-tile rows so the
    skip/cond path genuinely executes. Pad positions (segment 0) are
    excluded: their outputs are unspecified (zero when a tile is skipped,
    uniform-softmax garbage when not) and carry no loss or gradient."""
    fa = importlib.import_module(
        'bert_pytorch_tpu.ops.pallas.flash_attention')

    monkeypatch.setenv("FLASH_LAYOUT", layout)
    monkeypatch.setenv("FLASH_BWD", bwd)
    monkeypatch.setenv("FLASH_SEG_SKIP", skip)
    monkeypatch.setattr(fa, "DEFAULT_BLK_Q", 128)
    monkeypatch.setattr(fa, "DEFAULT_BLK_K", 128)

    q, k, v, seg = _packed_qkv()
    valid = jnp.array(np.asarray(seg) > 0)

    got = fa.flash_attention(q, k, v, segment_ids=seg, interpret=True)
    want = _dense_block_diag(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got)[np.asarray(valid)],
                               np.asarray(want)[np.asarray(valid)],
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        out = fa.flash_attention(q, k, v, segment_ids=seg, interpret=True)
        return jnp.sum(jnp.where(valid[..., None, None], out, 0.0) ** 2)

    def loss_ref(q, k, v):
        out = _dense_block_diag(q, k, v, seg)
        return jnp.sum(jnp.where(valid[..., None, None], out, 0.0) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_flash_segments_with_dropout_layout_parity(monkeypatch):
    """Dropout + segments: native and bh layouts draw identical keep-masks
    (cross-layout bit-parity contract), so outputs agree to float tolerance
    and zero patterns exactly on valid positions."""
    fa = importlib.import_module(
        'bert_pytorch_tpu.ops.pallas.flash_attention')

    monkeypatch.setattr(fa, "DEFAULT_BLK_Q", 128)
    monkeypatch.setattr(fa, "DEFAULT_BLK_K", 128)
    q, k, v, seg = _packed_qkv()
    seed = jnp.array(11, jnp.int32)
    valid = np.asarray(seg) > 0

    outs = {}
    for layout in ("native", "bh"):
        monkeypatch.setenv("FLASH_LAYOUT", layout)
        outs[layout] = np.asarray(fa.flash_attention(
            q, k, v, segment_ids=seg, dropout_seed=seed, dropout_rate=0.3,
            interpret=True))
    np.testing.assert_allclose(outs["native"][valid], outs["bh"][valid],
                               rtol=1e-6, atol=1e-6)


def test_flash_segments_no_cross_contamination_bit_identical(monkeypatch):
    """Perturbing every token of segment 1 leaves segments 2 and 3 of the
    same row BIT-identical — cross-segment probabilities are exact fp32
    zeros, not merely small."""
    fa = importlib.import_module(
        'bert_pytorch_tpu.ops.pallas.flash_attention')

    monkeypatch.setattr(fa, "DEFAULT_BLK_Q", 128)
    monkeypatch.setattr(fa, "DEFAULT_BLK_K", 128)
    for layout in ("native", "bh"):
        monkeypatch.setenv("FLASH_LAYOUT", layout)
        q, k, v, seg = _packed_qkv()
        seg_np = np.asarray(seg)
        q2 = q.at[0, :100].add(1.0)
        k2 = k.at[0, :100].add(-0.5)
        a = np.asarray(fa.flash_attention(q, k, v, segment_ids=seg,
                                          interpret=True))
        b = np.asarray(fa.flash_attention(q2, k2, v, segment_ids=seg,
                                          interpret=True))
        other = (seg_np[0] > 1)
        assert (a[0, other] == b[0, other]).all()
        # the untouched row is untouched
        assert (a[1] == b[1]).all()


def test_xla_fallback_matches_flash_segments():
    """dot_product_attention(impl='xla') with segment_ids — the parity
    fallback every non-TPU path uses — against the flash kernel in
    interpret mode."""
    from bert_pytorch_tpu.ops import attention
    fa = importlib.import_module(
        'bert_pytorch_tpu.ops.pallas.flash_attention')

    q, k, v, seg = _packed_qkv()
    valid = np.asarray(seg) > 0
    xla = np.asarray(attention.dot_product_attention(
        q, k, v, segment_ids=seg, impl="xla"))
    flash = np.asarray(fa.flash_attention(q, k, v, segment_ids=seg,
                                          interpret=True))
    np.testing.assert_allclose(xla[valid], flash[valid],
                               rtol=2e-5, atol=2e-5)


def test_pad_rows_zeroed_on_every_path(monkeypatch):
    """Pad (segment-0) positions produce EXACT-zero attention outputs on
    every forward path — both kernel layouts, skip on and off, and the XLA
    fallback — so downstream consumers of full (B, S, E) hidden states
    (K-FAC factor taps) see identical pad activations regardless of kernel
    configuration."""
    from bert_pytorch_tpu.ops import attention

    fa = importlib.import_module(
        'bert_pytorch_tpu.ops.pallas.flash_attention')
    monkeypatch.setattr(fa, "DEFAULT_BLK_Q", 128)
    monkeypatch.setattr(fa, "DEFAULT_BLK_K", 128)
    q, k, v, seg = _packed_qkv()
    pad = np.asarray(seg) == 0
    assert pad.any()
    for layout in ("native", "bh"):
        for skip in ("1", "0"):
            monkeypatch.setenv("FLASH_LAYOUT", layout)
            monkeypatch.setenv("FLASH_SEG_SKIP", skip)
            out = np.asarray(fa.flash_attention(q, k, v, segment_ids=seg,
                                                interpret=True))
            assert (out[pad] == 0.0).all(), (layout, skip)
    out = np.asarray(attention.dot_product_attention(
        q, k, v, segment_ids=seg, impl="xla"))
    assert (out[pad] == 0.0).all()


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_packed_forward_backward_on_seq_sharded_mesh():
    """Packing on a data x seq mesh — the composition that raised
    NotImplementedError through round 10. A packed forward+backward
    through the FULL model on the sharded mesh must match the unsharded
    packed reference (loss to the test_packed_loss_equals_unpacked pin,
    grads close), and rewriting segment 1's tokens must leave the other
    segments' MLM logits BIT-identical on the sharded path too."""
    from bert_pytorch_tpu.models import losses
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    cfg, model = _tiny_model(attention_impl="ring")
    ex, pk = _packed_equivalents()
    # batch 2 (identical rows) so the data axis has something to shard
    pk = {k: np.concatenate([v, v]) for k, v in pk.items()}
    ids, tok, am = (jnp.asarray(pk[k]) for k in
                    ("input_ids", "token_type_ids", "attention_mask"))
    packed_kw = dict(position_ids=jnp.asarray(pk["position_ids"]),
                     segment_ids=jnp.asarray(pk["segment_ids"]),
                     nsp_positions=jnp.asarray(pk["nsp_positions"]))
    params = model.init(jax.random.PRNGKey(0), ids, tok, am)["params"]

    def loss_fn(params, input_ids):
        ml, nl = model.apply({"params": params}, input_ids, tok, am,
                             deterministic=True, **packed_kw)
        return losses.pretraining_loss(
            ml, jnp.asarray(pk["masked_lm_labels"]), nl,
            jnp.asarray(pk["next_sentence_labels"]))

    # unsharded packed reference: impl='ring' without a mesh is the exact
    # dense path
    want, wgrads = jax.value_and_grad(loss_fn)(params, ids)

    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    with mesh, mesh_lib.logical_rules():
        got, ggrads = jax.value_and_grad(loss_fn)(params, ids)
    assert float(got) == pytest.approx(float(want), abs=2e-5)
    for a, b in zip(jax.tree.leaves(wgrads), jax.tree.leaves(ggrads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)

    # bit-exact no-contamination ON the sharded mesh: rewrite segment 1
    def mlm(input_ids):
        with mesh, mesh_lib.logical_rules():
            ml, _ = model.apply({"params": params}, input_ids, tok, am,
                                deterministic=True, **packed_kw)
        return np.asarray(ml)

    seg = np.asarray(pk["segment_ids"])
    ids2 = pk["input_ids"].copy()
    ids2[seg == 1] = 7
    ml_a, ml_b = mlm(ids), mlm(jnp.asarray(ids2))
    other = seg > 1
    np.testing.assert_array_equal(ml_a[other], ml_b[other])
    assert not np.allclose(ml_a[seg == 1], ml_b[seg == 1])


# -- model + loss -----------------------------------------------------------

def _tiny_model(**over):
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining

    kw = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=4, intermediate_size=64,
              max_position_embeddings=64, next_sentence=True,
              hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0,
              fused_ops=False, attention_impl="xla", dtype="float32")
    kw.update(over)
    cfg = BertConfig(**kw)
    return cfg, BertForPreTraining(cfg, dtype=jnp.float32)


def _packed_equivalents(lens=(10, 14, 8), max_segments=4, seed=0):
    ex = _example_batch(list(lens), seed=seed)
    bins = P.first_fit(P.example_lengths(ex["attention_mask"]), 1, SEQ,
                       max_segments)
    assert bins == [list(range(len(lens)))]  # all fit one row
    return ex, P.pack_examples(ex, bins, SEQ, max_segments)


def test_packed_loss_equals_unpacked():
    """The hand-checkable loss contract: one packed row of 3 examples (2
    masked tokens each) produces EXACTLY the unpacked batch's MLM+NSP loss,
    which (equal mask counts) also equals the mean of the per-example
    losses."""
    from bert_pytorch_tpu.models import losses

    cfg, model = _tiny_model()
    ex, pk = _packed_equivalents()
    ids, tok, am = (jnp.asarray(ex[k]) for k in
                    ("input_ids", "token_type_ids", "attention_mask"))
    params = model.init(jax.random.PRNGKey(0), ids, tok, am)["params"]

    ml, nl = model.apply({"params": params}, ids, tok, am,
                         deterministic=True)
    unpacked = float(losses.pretraining_loss(
        ml, jnp.asarray(ex["masked_lm_labels"]), nl,
        jnp.asarray(ex["next_sentence_labels"])))

    per_example = []
    for i in range(ids.shape[0]):
        mli, nli = model.apply({"params": params}, ids[i:i + 1],
                               tok[i:i + 1], am[i:i + 1],
                               deterministic=True)
        per_example.append(float(losses.pretraining_loss(
            mli, jnp.asarray(ex["masked_lm_labels"][i:i + 1]), nli,
            jnp.asarray(ex["next_sentence_labels"][i:i + 1]))))

    mlp, nlp = model.apply(
        {"params": params}, jnp.asarray(pk["input_ids"]),
        jnp.asarray(pk["token_type_ids"]),
        jnp.asarray(pk["attention_mask"]), deterministic=True,
        position_ids=jnp.asarray(pk["position_ids"]),
        segment_ids=jnp.asarray(pk["segment_ids"]),
        nsp_positions=jnp.asarray(pk["nsp_positions"]))
    assert nlp.shape == (1, 4, 2)  # per-segment NSP logits
    packed = float(losses.pretraining_loss(
        mlp, jnp.asarray(pk["masked_lm_labels"]), nlp,
        jnp.asarray(pk["next_sentence_labels"])))

    assert packed == pytest.approx(unpacked, abs=2e-5)
    assert packed == pytest.approx(np.mean(per_example), abs=2e-5)


def test_packed_model_no_cross_contamination_bit_identical():
    """End-to-end through the full model (XLA attention path): perturbing
    segment 1's tokens leaves segment 2/3 MLM logits and their NSP logits
    bit-identical."""
    cfg, model = _tiny_model()
    ex, pk = _packed_equivalents()
    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(ex["input_ids"]),
        jnp.asarray(ex["token_type_ids"]),
        jnp.asarray(ex["attention_mask"]))["params"]

    def run(input_ids):
        return model.apply(
            {"params": params}, jnp.asarray(input_ids),
            jnp.asarray(pk["token_type_ids"]),
            jnp.asarray(pk["attention_mask"]), deterministic=True,
            position_ids=jnp.asarray(pk["position_ids"]),
            segment_ids=jnp.asarray(pk["segment_ids"]),
            nsp_positions=jnp.asarray(pk["nsp_positions"]))

    ids2 = pk["input_ids"].copy()
    seg = pk["segment_ids"][0]
    ids2[0, seg == 1] = 7  # rewrite every token of segment 1
    ml_a, nsp_a = run(pk["input_ids"])
    ml_b, nsp_b = run(ids2)
    other = np.asarray(seg) > 1
    assert (np.asarray(ml_a)[0, other] == np.asarray(ml_b)[0, other]).all()
    # segment 1's NSP slot changes; segments 2 and 3 stay bit-identical.
    # (Empty slots gather row position 0 — segment 1's [CLS] — by design;
    # their label is -1 so the loss never reads them.)
    n_real = int(np.asarray(seg).max())
    assert (np.asarray(nsp_a)[0, 1:n_real]
            == np.asarray(nsp_b)[0, 1:n_real]).all()
    assert not (np.asarray(nsp_a)[0, 0] == np.asarray(nsp_b)[0, 0]).all()


def test_packed_model_remat_and_unstacked_variants():
    """The segment threading survives nn.remat (static_argnums shifted to
    4) and the unstacked per-layer encoder: both variants produce the same
    logits as the plain stacked forward."""
    ex, pk = _packed_equivalents()
    args = dict(deterministic=True,
                position_ids=jnp.asarray(pk["position_ids"]),
                segment_ids=jnp.asarray(pk["segment_ids"]),
                nsp_positions=jnp.asarray(pk["nsp_positions"]))
    ids, tok, am = (jnp.asarray(pk[k]) for k in
                    ("input_ids", "token_type_ids", "attention_mask"))

    cfg, base = _tiny_model()
    params = base.init(jax.random.PRNGKey(0), ids, tok, am)["params"]
    want_ml, want_nsp = base.apply({"params": params}, ids, tok, am, **args)

    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.models.pretrained import unstack_layer_tree

    remat = BertForPreTraining(cfg.replace(checkpoint_activations=True),
                               dtype=jnp.float32)
    got_ml, got_nsp = remat.apply({"params": params}, ids, tok, am, **args)
    np.testing.assert_allclose(np.asarray(got_ml), np.asarray(want_ml),
                               rtol=1e-6, atol=1e-6)

    unstacked = BertForPreTraining(cfg.replace(stacked_params=False),
                                   dtype=jnp.float32)
    got_ml, got_nsp = unstacked.apply(
        {"params": unstack_layer_tree(params)}, ids, tok, am, **args)
    np.testing.assert_allclose(np.asarray(got_ml), np.asarray(want_ml),
                               rtol=1e-5, atol=1e-5)


def test_pretrain_step_accepts_packed_batch():
    """build_pretrain_step threads the packed fields end to end: one
    optimizer step over a packed batch runs, updates params, and reports
    finite metrics (the K-FAC builder shares the same _packed_kwargs
    plumbing)."""
    import optax

    from bert_pytorch_tpu.training.pretrain import (build_pretrain_step,
                                                    stack_microbatches)

    cfg, model = _tiny_model()
    ex, pk = _packed_equivalents()
    batch = stack_microbatches(pk, 1)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    params = model.init(
        jax.random.PRNGKey(0), batch["input_ids"][0],
        batch["token_type_ids"][0], batch["attention_mask"][0])["params"]
    tx = optax.sgd(1e-2)

    from bert_pytorch_tpu.training.state import TrainState

    state = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       opt_state=tx.init(params))
    step = build_pretrain_step(model, tx, accum_steps=1, max_predictions=8)
    new_state, metrics = jax.jit(step)(state, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["mlm_accuracy"]) >= 0.0
    # params moved
    leaf = jax.tree.leaves(params)[0]
    new_leaf = jax.tree.leaves(new_state.params)[0]
    assert not np.allclose(np.asarray(leaf), np.asarray(new_leaf))


# -- telemetry --------------------------------------------------------------

def test_stepwatch_real_tokens_and_pad_fraction():
    from bert_pytorch_tpu.telemetry.stepwatch import StepWatch

    t = [0.0]
    sw = StepWatch(flops_per_step=1e9, seqs_per_step=4, seq_len=128,
                   peak_flops=1e12, log_freq=2, time_fn=lambda: t[0])
    # two steps, 4 rows x 128 slots each = 1024 slot tokens, 768 real
    sw.note_tokens(384)
    t[0] += 1.0
    assert sw.step_done() is None
    sw.note_tokens(384)
    t[0] += 1.0
    rec = sw.step_done()
    assert rec is not None
    assert rec["real_tokens_per_sec"] == pytest.approx(768 / 2.0)
    assert rec["packing_efficiency"] == pytest.approx(768 / 1024)
    assert rec["pad_fraction"] == pytest.approx(1 - 768 / 1024)
    # tokens_per_sec still counts slots — the hardware-occupancy number
    assert rec["tokens_per_sec"] == pytest.approx(4 * 128 * 2 / 2.0)
    # without note_tokens the fields stay absent (pre-round-9 records)
    sw2 = StepWatch(flops_per_step=1e9, seqs_per_step=4, seq_len=128,
                    peak_flops=1e12, log_freq=1, time_fn=lambda: t[0])
    t[0] += 1.0
    rec2 = sw2.step_done()
    assert "pad_fraction" not in rec2 and "real_tokens_per_sec" not in rec2


def test_packing_efficiency_helper():
    seg = np.array([[1, 1, 2, 0], [1, 0, 0, 0]])
    assert P.packing_efficiency(seg) == pytest.approx(4 / 8)
