"""The phase-agnostic metrics plane: registry, /metrics exporter,
init_run handle, multi-host aggregation, and a LIVE scrape of a real
CPU pretraining run with an injected-NaN step.

Executable contracts for docs/OBSERVABILITY.md "Live metrics" — in
particular the acceptance path: `GET /metrics` during a running job is
Prometheus-parseable and carries the step counter / step-time gauge /
nonfinite counters, and `/healthz` reflects the injected-NaN step.
"""

import io
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.telemetry.registry import (  # noqa: E402
    MetricsRegistry, parse_prometheus)
from bert_pytorch_tpu.telemetry.exporter import MetricsServer  # noqa: E402
from bert_pytorch_tpu.telemetry.multihost import (  # noqa: E402
    HostMetricsAggregator, host_file, read_last_record)
from bert_pytorch_tpu.telemetry.run import (  # noqa: E402
    PERF_RECORD_CORE_KEYS, init_run)
from tests.test_data import write_shard  # noqa: E402


# -- registry -----------------------------------------------------------------

def test_counter_gauge_histogram_render_and_parse():
    r = MetricsRegistry(constant_labels={"phase": "t"})
    c = r.counter("steps_total", "steps")
    c.inc()
    c.inc(2)
    g = r.gauge("speed", "seq/s", labels=("kind",))
    g.set(10.5, kind="real")
    g.set(12.0, kind="slot")
    h = r.histogram("lat_ms", "latency", buckets=(10, 100))
    for v in (5, 50, 500, 50):
        h.observe(v)
    parsed = parse_prometheus(r.render_prometheus())
    assert parsed["steps_total"]['{phase="t"}'] == 3
    assert parsed["speed"]['{phase="t",kind="real"}'] == 10.5
    assert parsed["speed"]['{phase="t",kind="slot"}'] == 12.0
    # cumulative buckets: <=10 -> 1, <=100 -> 3, +Inf -> 4
    assert parsed["lat_ms_bucket"]['{phase="t",le="10"}'] == 1
    assert parsed["lat_ms_bucket"]['{phase="t",le="100"}'] == 3
    assert parsed["lat_ms_bucket"]['{phase="t",le="+Inf"}'] == 4
    assert parsed["lat_ms_sum"]['{phase="t"}'] == 605
    assert parsed["lat_ms_count"]['{phase="t"}'] == 4


def test_labelless_families_expose_zero_before_first_event():
    """/metrics must show the declared zeros from the first scrape — a
    counter that only appears after its first inc is indistinguishable
    from a counter that does not exist."""
    r = MetricsRegistry()
    r.counter("bert_nonfinite_steps_total")
    r.gauge("bert_step_time_ms")
    parsed = parse_prometheus(r.render_prometheus())
    assert parsed["bert_nonfinite_steps_total"][""] == 0
    assert parsed["bert_step_time_ms"][""] == 0


def test_get_or_create_and_kind_mismatch():
    r = MetricsRegistry()
    a = r.counter("x_total", "first")
    b = r.counter("x_total", "second declare returns the same family")
    assert a is b
    with pytest.raises(ValueError, match="already declared"):
        r.gauge("x_total")
    with pytest.raises(ValueError, match="declared labels"):
        a.inc(1, unexpected="label")


def test_counter_monotonic_inc_to():
    r = MetricsRegistry()
    c = r.counter("compiles_total")
    c.inc_to(5)
    c.inc_to(3)  # sampled source went backwards: counter must not
    assert c.value() == 5
    with pytest.raises(ValueError, match="negative"):
        c.inc(-1)


def test_label_escaping_and_snapshot_strict_json():
    r = MetricsRegistry()
    g = r.gauge("g", labels=("path",))
    g.set(1.0, path='a"b\\c\nd')
    text = r.render_prometheus()
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    g.set(float("nan"), path="bad")
    # snapshot is strict JSON (non-finite -> repr strings), the form that
    # rides in flight-recorder manifests
    snap_json = r.snapshot_json()
    snap = json.loads(snap_json)
    vals = {s["labels"]["path"]: s["value"] for s in snap["g"]["series"]}
    assert vals["bad"] == "nan"


# -- exporter -----------------------------------------------------------------

def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def test_metrics_server_serves_and_404s():
    r = MetricsRegistry(constant_labels={"phase": "srv"})
    r.counter("up_total").inc()
    srv = MetricsServer(r, healthz_fn=lambda: {"phase": "srv", "ok": 1},
                        port=0, host="127.0.0.1")
    try:
        parsed = parse_prometheus(_get(srv.url + "/metrics"))
        assert parsed["up_total"]['{phase="srv"}'] == 1
        hz = json.loads(_get(srv.url + "/healthz"))
        assert hz == {"phase": "srv", "ok": 1}
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(srv.url + "/nope")
        assert e.value.code == 404
    finally:
        srv.close()
        srv.close()  # idempotent


# -- init_run handle ----------------------------------------------------------

def test_init_run_wires_registry_health_and_perf(tmp_path):
    tel = init_run(phase="unit", log_prefix=str(tmp_path / "log"),
                   stream=io.StringIO(), jsonl=True)
    try:
        sw = tel.make_stepwatch(flops_per_step=1e9, seqs_per_step=8,
                                seq_len=64, peak_flops=1e12, log_freq=2)
        assert sw is tel.stepwatch
        sw.step_done()
        rec = sw.step_done()
        assert rec is not None
        logged = tel.log_perf(2, rec)
        assert set(PERF_RECORD_CORE_KEYS) <= set(logged)
        tel.log_train(2, step_loss=1.5, loss_nonfinite=0, grad_nonfinite=0)
        tel.log_train(3, step_loss=float("nan"), loss_nonfinite=1,
                      grad_nonfinite=4)
        parsed = parse_prometheus(tel.registry.render_prometheus())
        lab = '{phase="unit"}'
        assert parsed["bert_train_steps_total"][lab] == 2
        assert parsed["bert_nonfinite_steps_total"][lab] == 1
        assert parsed["bert_loss_nonfinite_steps_total"][lab] == 1
        assert parsed["bert_grad_nonfinite_steps_total"][lab] == 1
        assert parsed["bert_step_time_ms"][lab] == rec["step_time_ms"]
        # MetricLogger published the record values as tagged gauges too
        assert parsed["bert_metric"][
            '{phase="unit",tag="train",name="grad_nonfinite"}'] == 4
        assert parsed["bert_last_logged_step"][
            '{phase="unit",tag="train"}'] == 3
        hz = tel.healthz()
        assert hz["phase"] == "unit"
        assert hz["last_step"] == 3
        assert hz["last_nonfinite_step"] == 3
        assert hz["nonfinite_flags"]["grad_nonfinite"] == 4
        assert hz["last_perf"]["step_time_ms"] == rec["step_time_ms"]
    finally:
        tel.close()
        tel.close()  # idempotent


# -- multi-host aggregation ---------------------------------------------------

def _write_host(dirpath, host, step, step_time_ms, data_wait_ms=1.0):
    agg = HostMetricsAggregator(str(dirpath), process_index=host,
                                process_count=4)
    agg.publish(step, {"step_time_ms": step_time_ms,
                       "data_wait_ms": data_wait_ms,
                       "ignored_str": "x", "nan_skipped": float("nan")})
    agg.close()


def test_aggregator_fold_min_mean_max_and_straggler(tmp_path):
    d = tmp_path / "hosts"
    for host, stms in enumerate((100.0, 110.0, 105.0, 400.0)):
        _write_host(d, host, step=10 + host, step_time_ms=stms)
    agg = HostMetricsAggregator(str(d), process_index=0, process_count=4,
                                z_threshold=1.5)
    try:
        folded, warning = agg.fold()
        assert folded["hosts_reporting"] == 4
        assert folded["hosts_step_min"] == 10
        assert folded["hosts_step_max"] == 13
        assert folded["step_time_ms_host_min"] == 100.0
        assert folded["step_time_ms_host_max"] == 400.0
        assert folded["step_time_ms_host_mean"] == pytest.approx(178.75)
        assert folded["data_wait_ms_host_max"] == 1.0
        # host 3 z-scores far above the fleet: flagged + warned
        assert folded["straggler_host"] == 3
        assert folded["straggler_z"] > 1.5
        assert warning and "host 3" in warning
    finally:
        agg.close()


def test_aggregator_single_host_is_silent(tmp_path):
    d = tmp_path / "hosts"
    agg = HostMetricsAggregator(str(d), process_index=0, process_count=1)
    try:
        agg.publish(1, {"step_time_ms": 50.0})
        folded, warning = agg.fold()
        assert folded == {} and warning is None
    finally:
        agg.close()


def test_aggregator_balanced_fleet_no_straggler(tmp_path):
    d = tmp_path / "hosts"
    for host in range(3):
        _write_host(d, host, step=5, step_time_ms=100.0 + host)
    agg = HostMetricsAggregator(str(d), process_index=0, process_count=3,
                                z_threshold=3.0)
    try:
        folded, warning = agg.fold()
        assert folded["hosts_reporting"] == 3
        assert "straggler_host" not in folded
        assert warning is None
    finally:
        agg.close()


def test_read_last_record_tolerates_torn_tail(tmp_path):
    d = tmp_path / "hosts"
    d.mkdir()
    path = host_file(str(d), 0)
    with open(path, "w") as f:
        f.write(json.dumps({"step": 1, "step_time_ms": 10}) + "\n")
        f.write('{"step": 2, "step_time_ms"')  # torn concurrent append
    rec = read_last_record(path)
    assert rec == {"step": 1, "step_time_ms": 10}
    assert read_last_record(host_file(str(d), 7)) is None


def test_log_perf_publishes_and_process0_folds(tmp_path):
    """init_run end-to-end over a shared dir: two handles acting as two
    hosts; process 0's log_perf record comes back fold-augmented."""
    shared = str(tmp_path / "metrics_hosts")
    tel1 = init_run(phase="pretrain", stream=io.StringIO(),
                    multihost_dir=shared, process_index=1, process_count=2,
                    straggler_z=0.5)
    rec = {"steps": 10, "step_time_ms": 300.0, "seq_per_sec": 2.0,
           "tokens_per_sec": 128.0, "model_flops_per_sec": 1e9,
           "mfu": 0.1, "peak_flops": 1e12}
    tel1.log_perf(10, rec)
    tel0 = init_run(phase="pretrain", stream=io.StringIO(),
                    multihost_dir=shared, process_index=0, process_count=2,
                    straggler_z=0.5)
    try:
        logged = tel0.log_perf(10, dict(rec, step_time_ms=100.0))
        assert logged["hosts_reporting"] == 2
        assert logged["step_time_ms_host_min"] == 100.0
        assert logged["step_time_ms_host_max"] == 300.0
        # with two hosts both sit at |z|=1; threshold 0.5 flags the slow one
        assert logged["straggler_host"] == 1
    finally:
        tel0.close()
        tel1.close()


# -- live /metrics against a real run ----------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_live_metrics_during_pretraining_with_injected_nan(tmp_path):
    """Acceptance: scrape /metrics + /healthz WHILE run_pretraining.main
    trains on the CPU mesh with --inject_nonfinite_step — the text is
    Prometheus-parseable with the step counter / step-time gauge /
    nonfinite counters, and /healthz names the injected-NaN step."""
    import run_pretraining

    data = tmp_path / "data"
    data.mkdir()
    for i in range(2):
        write_shard(data / f"shard_{i}.hdf5", 64, seed=i)
    model_cfg = {
        "vocab_size": 128, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "tokenizer": "wordpiece", "fused_ops": False,
        "attention_impl": "xla",
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))
    port = _free_port()
    out = tmp_path / "out"
    argv = ["--model_config_file", str(cfg_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--learning_rate", "1e-3", "--global_batch_size", "32",
            "--local_batch_size", "2", "--max_steps", "40",
            "--max_predictions_per_seq", "5", "--skip_checkpoint",
            "--log_freq", "2", "--flight_recorder", "off",
            "--metrics_port", str(port), "--inject_nonfinite_step", "3"]

    result = {}

    def run():
        try:
            result["final"] = run_pretraining.main(argv)
        except BaseException as e:  # surfaced by the assert below
            result["exc"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    lab = '{phase="pretrain"}'
    caught = None
    deadline = time.time() + 300
    while time.time() < deadline and (t.is_alive() or caught is None):
        try:
            text = _get(base + "/metrics", timeout=2)
            hz = json.loads(_get(base + "/healthz", timeout=2))
        except Exception:
            time.sleep(0.02)
            continue
        parsed = parse_prometheus(text)
        steps = parsed.get("bert_train_steps_total", {}).get(lab, 0)
        # action=log applies the poisoned update, so every step AFTER the
        # injected one is non-finite too — last_nonfinite_step advances
        # with the run; >= 3 is the non-racy "the injection was seen"
        nf = hz.get("last_nonfinite_step")
        if steps >= 4 and nf is not None and nf >= 3:
            caught = (parsed, hz)
            break
        time.sleep(0.02)
    t.join(timeout=300)
    assert "exc" not in result, result.get("exc")
    assert caught is not None, (
        "never caught a live scrape with >=4 steps and the injected-NaN "
        f"step in /healthz (run result: {result})")
    parsed, hz = caught
    assert parsed["bert_train_steps_total"][lab] >= 4
    assert "bert_step_time_ms" in parsed          # perf gauge
    assert parsed["bert_nonfinite_steps_total"][lab] >= 1
    assert parsed["bert_loss_nonfinite_steps_total"][lab] >= 1
    assert "bert_step_time_ms_hist_count" in parsed
    assert hz["phase"] == "pretrain"
    assert hz["last_nonfinite_step"] >= 3
    assert hz["nonfinite_flags"].get("loss_nonfinite", 0) >= 1 \
        or hz["nonfinite_flags"].get("grad_nonfinite", 0) >= 1
    # the run itself finished cleanly (action=log trains through the NaN)
    assert result.get("final", (0,))[0] == 40
