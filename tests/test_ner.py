"""NER tests: CoNLL parsing, label propagation + [SPC]/-100 framing, padding,
macro-F1, and the end-to-end runner on a tiny model."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.data import ner
from bert_pytorch_tpu.data.tokenization import BertWordPieceTokenizer

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "john", "smith", "works", "at", "acme", "corp", "in", "london",
         "##s", "said", "."]

CONLL = """-DOCSTART- -X- -X- O

John NNP B-NP B-PER
Smith NNP I-NP I-PER
works VBZ B-VP O
at IN B-PP O
Acme NNP B-NP B-ORG
Corp NNP I-NP I-ORG
. . O O

London NNP B-NP B-LOC
said VBD B-VP O
"""

LABELS = ["O", "B-PER", "I-PER", "B-ORG", "I-ORG", "B-LOC", "I-LOC"]


@pytest.fixture
def tokenizer(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n")
    return BertWordPieceTokenizer(str(p), lowercase=True)


@pytest.fixture
def conll_file(tmp_path):
    p = tmp_path / "train.conll"
    p.write_text(CONLL)
    return str(p)


def test_parse_conll(conll_file):
    samples = ner.parse_conll(conll_file)
    assert len(samples) == 2  # DOCSTART line excluded, blank-line split
    assert samples[0].words == ["John", "Smith", "works", "at", "Acme",
                                "Corp", "."]
    assert samples[0].labels == ["B-PER", "I-PER", "O", "O", "B-ORG",
                                 "I-ORG", "O"]
    assert samples[1].words == ["London", "said"]


def test_encode_label_propagation_and_framing(conll_file, tokenizer):
    ds = ner.NERDataset(conll_file, tokenizer, LABELS, max_seq_len=16)
    arrays = ds.arrays()
    assert arrays["input_ids"].shape == (2, 16)

    ids, labels, mask = arrays["input_ids"][0], arrays["labels"][0], \
        arrays["attention_mask"][0]
    # [CLS] framing with ignored label
    assert ids[0] == tokenizer.token_to_id("[CLS]")
    assert labels[0] == ner.IGNORE_LABEL
    # first word 'John' -> 'john', label B-PER = index 2 (start=1, O=1)
    assert ids[1] == tokenizer.token_to_id("john")
    assert labels[1] == ds.label_to_id["B-PER"] == 2
    # padding: ignored label so the loss never trains padding positions
    assert (labels[mask == 0] == ner.IGNORE_LABEL).all()
    # [SEP] ignored
    sep_pos = int(np.where(ids == tokenizer.token_to_id("[SEP]"))[0][0])
    assert labels[sep_pos] == ner.IGNORE_LABEL


def test_truncation(tokenizer, tmp_path):
    words = ["john"] * 50
    p = tmp_path / "long.conll"
    p.write_text("\n".join(f"{w} X Y O" for w in words) + "\n")
    ds = ner.NERDataset(str(p), tokenizer, LABELS, max_seq_len=16)
    ids, labels, mask = ds.samples[0].encode(tokenizer, ds.label_to_id, 16)
    assert len(ids) == 16 and mask == [1] * 16
    assert ids[-1] == tokenizer.token_to_id("[SEP]")


def test_macro_f1():
    # 3 classes, perfect prediction -> 1.0
    logits = np.zeros((1, 4, 4))
    labels = np.array([[1, 2, 3, 0]])  # final 0 = padding, excluded
    for i, l in enumerate(labels[0]):
        logits[0, i, l] = 5.0
    assert ner.macro_f1(logits, labels) == 1.0
    # all-wrong -> 0.0
    logits2 = np.zeros((1, 3, 4))
    logits2[:, :, 3] = 5.0
    assert ner.macro_f1(logits2, np.array([[1, 2, 1]])) == 0.0


def test_run_ner_end_to_end(tmp_path, conll_file):
    vocab_path = tmp_path / "vocab.txt"
    vocab_path.write_text("\n".join(VOCAB) + "\n")
    cfg = {
        "vocab_size": len(VOCAB), "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 32, "next_sentence": False,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "fused_ops": False, "attention_impl": "xla", "lowercase": True,
        "tokenizer": "wordpiece", "vocab_file": str(vocab_path),
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(cfg))

    import run_ner

    out = tmp_path / "out"
    results = run_ner.main([
        "--train_file", conll_file, "--val_file", conll_file,
        "--test_file", conll_file,
        "--labels", *LABELS,
        "--model_config_file", str(cfg_path),
        "--epochs", "40", "--lr", "1e-3", "--batch_size", "2",
        "--max_seq_len", "32", "--output_dir", str(out),
        "--dtype", "float32",
    ])
    assert "val_f1" in results and "test_f1" in results
    # the runner must actually LEARN: overfitting these two sentences has to
    # beat the all-O macro-F1 floor by a wide margin (a frozen/all-majority
    # classifier sits near 1/len(labels))
    assert results["test_f1"] > 0.8, results
    log = (out / "ner_log.txt").read_text()
    assert "macro_f1" in log

    # phase-agnostic perf schema (telemetry/run.py init_run): the ner
    # phase's StepWatch interval records carry the same core keys the
    # pretrain and squad e2e tests assert on
    from bert_pytorch_tpu.telemetry import PERF_RECORD_CORE_KEYS

    perf = [json.loads(line)
            for line in (out / "ner_log.jsonl").read_text().splitlines()
            if json.loads(line).get("tag") == "perf"]
    assert perf, "no perf records reached the ner jsonl sink"
    assert set(PERF_RECORD_CORE_KEYS) <= set(perf[-1]), perf[-1]
