"""SLO plane: burn-rate engine, canary prober, alert wiring (round 20).

Unit layer (fake clock, no server): the multi-window multi-burn-rate
math — an alert fires only when the burn exceeds its threshold in BOTH
windows of a severity's pair, resolves within one short window of the
burn stopping, respects min_events, and links firing latency alerts to
the trace ring. Plus the Prometheus exposition round-trip the engine
reads through, and the prober's decode verifiers.

E2E layer (ONE module-scoped server, all five registered tasks): the
three drill proofs the issue pins —
  * a clean run completes with ZERO alerts and /healthz status ok;
  * corrupt_answers is caught by the prober's known-answer decode
    verification (not a status code) and is LOCALIZED: exactly the
    injected task flips unhealthy, the other four stay ok, while real
    traffic on an uninjected task still answers 200;
  * error_burst trips the availability PAGE alert within one
    fast-window evaluation and resolves after the burst stops.
scripts/check_slo.sh re-proves the same drills subprocess-level with
the real --slo_inject arming path.
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bert_pytorch_tpu.serving.prober import (  # noqa: E402
    KNOWN_ANSWER_PAYLOADS, VERIFIERS, canonicalize)
from bert_pytorch_tpu.serving.request_trace import TraceRing  # noqa: E402
from bert_pytorch_tpu.telemetry.registry import (  # noqa: E402
    MetricsRegistry, parse_prometheus, parse_prometheus_labels)
from bert_pytorch_tpu.telemetry.slo import (  # noqa: E402
    DEFAULT_WINDOWS, FaultInjector, SLOEngine, _negate_tree,
    load_slo_config)

TINY_WINDOWS = {
    "page": {"short_s": 4.0, "long_s": 16.0, "burn_rate": 2.0},
    "ticket": {"short_s": 8.0, "long_s": 32.0, "burn_rate": 1.5},
}


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def _availability_engine(budget=0.05, min_events=3, registry=None,
                         clock=None, **spec_extra):
    clock = clock or FakeClock()
    reg = registry or MetricsRegistry()
    reg.counter("bert_serve_requests_total", "reqs",
                labels=("task", "outcome"))
    from bert_pytorch_tpu.telemetry.slo import SLOSpec

    spec = SLOSpec(dict({"name": "availability", "kind": "availability",
                         "budget": budget, "min_events": min_events},
                        **spec_extra), "serve")
    eng = SLOEngine([spec], TINY_WINDOWS, reg, phase="serve",
                    time_fn=clock)
    return eng, reg, clock


# -- config loading -----------------------------------------------------------


def test_checked_in_slo_config_loads():
    cfg = load_slo_config(os.path.join(REPO, "configs", "slo.json"))
    assert [s.name for s in cfg.specs_for("serve")] == [
        "availability", "latency_p99", "cost_per_1k_tokens"]
    assert [s.name for s in cfg.specs_for("train")] == [
        "step_time", "checkpoint_freshness", "nonfinite_rate"]
    # windows merge over the SRE-workbook defaults
    assert cfg.windows["page"]["short_s"] == 300.0
    assert cfg.windows["page"]["burn_rate"] == pytest.approx(14.4)
    assert cfg.windows["ticket"]["long_s"] == 21600.0
    assert set(DEFAULT_WINDOWS) == {"page", "ticket"}


def test_slo_config_validation(tmp_path):
    def write(doc):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps(doc))
        return str(p)

    with pytest.raises(ValueError, match="kind"):
        load_slo_config(write({"serve": [{"name": "x", "kind": "nope"}]}))
    with pytest.raises(ValueError, match="duplicate"):
        load_slo_config(write({"serve": [
            {"name": "x", "kind": "availability", "budget": 0.1},
            {"name": "x", "kind": "availability", "budget": 0.1}]}))
    with pytest.raises(ValueError, match="budget"):
        load_slo_config(write({"serve": [
            {"name": "x", "kind": "availability", "budget": 1.5}]}))
    with pytest.raises(ValueError, match="short_s"):
        load_slo_config(write({
            "windows": {"page": {"short_s": 60, "long_s": 5,
                                 "burn_rate": 2}},
            "serve": [{"name": "x", "kind": "availability",
                       "budget": 0.1}]}))
    with pytest.raises(ValueError, match="phase"):
        load_slo_config(write({"deploy": [
            {"name": "x", "kind": "availability", "budget": 0.1}]}))


# -- burn-rate engine ---------------------------------------------------------


def test_availability_burst_pages_and_resolves():
    eng, reg, clock = _availability_engine()
    c = reg.counter("bert_serve_requests_total", "reqs",
                    labels=("task", "outcome"))
    # priming tick: pre-engine history is baseline, not a burst
    for _ in range(50):
        c.inc(task="squad", outcome="error")
    eng.evaluate()
    v = eng.alerts_view()
    assert v["status"] == "ok" and not v["firing"]

    # clean traffic, then a sustained error burst
    for _ in range(3):
        clock.tick()
        for _ in range(20):
            c.inc(task="squad", outcome="ok")
        eng.evaluate()
    assert eng.alerts_view()["status"] == "ok"
    for _ in range(2):
        clock.tick()
        for _ in range(20):
            c.inc(task="squad", outcome="error")
        eng.evaluate()
    v = eng.alerts_view()
    assert v["status"] == "failing"
    fired = {(a["slo"], a["severity"]) for a in v["firing"]}
    assert ("availability", "page") in fired
    a = v["firing"][0]
    assert a["phase"] == "serve" and a["since_unix"] > 0
    assert a["windows"]["burn_threshold"] > 0
    assert a["burn_short"] > TINY_WINDOWS["page"]["burn_rate"]

    # burn stops -> the page pair resolves within ONE short window
    fire_t = clock.t
    while eng.alerts_view()["firing"]:
        clock.tick()
        for _ in range(50):
            c.inc(task="squad", outcome="ok")
        eng.evaluate()
        assert clock.t - fire_t < 40, "alert never resolved"
    v = eng.alerts_view()
    assert v["status"] == "ok"
    assert {(a["slo"], a["severity"]) for a in v["resolved"]} >= {
        ("availability", "page")}
    assert all(a["resolved_unix"] >= a["since_unix"]
               for a in v["resolved"])


def test_min_events_guard_prevents_sparse_false_page():
    eng, reg, clock = _availability_engine(min_events=10)
    c = reg.counter("bert_serve_requests_total", "reqs",
                    labels=("task", "outcome"))
    eng.evaluate()
    # 2 bad events out of 2: 100% bad fraction, but under min_events
    clock.tick()
    c.inc(task="squad", outcome="error")
    c.inc(task="squad", outcome="error")
    eng.evaluate()
    assert eng.alerts_view()["status"] == "ok"


def test_latency_spec_links_slowest_traces():
    from bert_pytorch_tpu.telemetry.slo import SLOSpec

    clock = FakeClock()
    reg = MetricsRegistry()
    h = reg.histogram("bert_serve_request_latency_ms", "lat",
                      buckets=(1, 10, 100, 1000), labels=("task",))
    ring = TraceRing(keep_slowest=4, sample_every=1, window_s=3600.0)
    tr = ring.new_trace("squad", t_admit=0.0)
    tr.span("compute", 0.0, 0.8)
    tr.finish("ok", 0.9)
    ring.add(tr)
    spec = SLOSpec({"name": "latency_p99", "kind": "latency",
                    "bound_ms": 100, "budget": 0.05, "min_events": 3},
                   "serve")
    eng = SLOEngine([spec], TINY_WINDOWS, reg, phase="serve",
                    trace_ring=ring, time_fn=clock)
    eng.evaluate()
    for _ in range(3):
        clock.tick()
        for _ in range(10):
            h.observe(800.0, task="squad")  # above the 100ms bound
        eng.evaluate()
    v = eng.alerts_view()
    assert v["status"] == "failing"
    lat = [a for a in v["firing"] if a["slo"] == "latency_p99"]
    assert lat, v["firing"]
    # the firing alert names in-ring trace ids tools/trace_summary.py
    # --ids can consume directly
    assert tr.trace_id in lat[0]["trace_ids"]


def test_threshold_and_counter_ratio_train_specs():
    from bert_pytorch_tpu.telemetry.slo import SLOSpec

    clock = FakeClock()
    reg = MetricsRegistry()
    g = reg.gauge("bert_step_time_ms", "step time", labels=("host",))
    bad = reg.counter("bert_nonfinite_steps_total", "nf")
    tot = reg.counter("bert_train_steps_total", "steps")
    specs = [
        SLOSpec({"name": "step_time", "kind": "threshold",
                 "source": "gauge:bert_step_time_ms", "agg": "max",
                 "bound": 100.0, "direction": "above", "budget": 0.05,
                 "skip_zero": True}, "train"),
        SLOSpec({"name": "checkpoint_freshness", "kind": "threshold",
                 "source": "checkpoint_age_s", "bound": 60.0,
                 "direction": "above", "budget": 0.05}, "train"),
        SLOSpec({"name": "nonfinite_rate", "kind": "counter_ratio",
                 "bad_metric": "bert_nonfinite_steps_total",
                 "total_metric": "bert_train_steps_total",
                 "budget": 0.05, "min_events": 5}, "train"),
    ]
    eng = SLOEngine(specs, TINY_WINDOWS, reg, phase="train",
                    time_fn=clock)
    age = [0.0]
    eng.set_source("checkpoint_age_s", lambda: age[0])
    g.set(0.0, host="h0")  # skip_zero: an unset gauge is not a breach
    eng.evaluate()
    for _ in range(5):
        clock.tick()
        g.set(50.0, host="h0")
        tot.inc()
        eng.evaluate()
    assert eng.alerts_view()["status"] == "ok"

    # all three breach together: slow steps, stale checkpoint, NaN storm
    for _ in range(6):
        clock.tick()
        g.set(500.0, host="h0")
        age[0] = 999.0
        bad.inc()
        tot.inc()
        eng.evaluate()
    firing = {a["slo"] for a in eng.alerts_view()["firing"]}
    assert {"step_time", "checkpoint_freshness",
            "nonfinite_rate"} <= firing
    # threshold alerts carry the observed value vs the bound
    st = [a for a in eng.alerts_view()["firing"]
          if a["slo"] == "step_time"][0]
    assert st["value"] == 500.0 and st["bound"] == 100.0
    assert eng.page_firing_since() is not None


def test_external_alert_source_folds_into_status():
    eng, reg, clock = _availability_engine()
    eng.evaluate()
    assert eng.status() == "ok"
    external = []
    eng.add_alert_source(lambda: external)
    external.append({"slo": "probe_squad", "severity": "page",
                     "source": "prober", "since_unix": clock()})
    v = eng.alerts_view()
    assert v["status"] == "failing"
    assert any(a["slo"] == "probe_squad" for a in v["firing"])
    hs = eng.health_summary()
    assert hs["status"] == "failing"
    assert "probe_squad:page" in hs["firing"]
    external.clear()
    assert eng.status() == "ok"


# -- exposition round-trip (satellite: /metrics hardening) --------------------


def test_prometheus_exposition_roundtrip_nasty_values():
    reg = MetricsRegistry()
    c = reg.counter("bert_test_total", 'help with \\ and\nnewline',
                    labels=("path", "q"))
    nasty = 'a"b\\c\nd,e}f=g'
    c.inc(7, path=nasty, q="plain")
    h = reg.histogram("bert_test_ms", "hist", buckets=(1, 10),
                      labels=("task",))
    for v in (0.5, 5.0, 50.0):
        h.observe(v, task=nasty)
    text = reg.render_prometheus()
    # HELP lines survive as single lines (newline escaped, not emitted)
    help_lines = [ln for ln in text.splitlines()
                  if ln.startswith("# HELP bert_test_total")]
    assert len(help_lines) == 1 and "\\n" in help_lines[0]

    fams = parse_prometheus(text)
    label_chunk = next(k for k in fams["bert_test_total"] if k)
    labels = parse_prometheus_labels(label_chunk)
    assert labels == {"path": nasty, "q": "plain"}
    assert fams["bert_test_total"][label_chunk] == 7.0

    # histogram contract: +Inf-terminated cumulative buckets, and the
    # +Inf bucket == _count; _sum matches the observations
    hb = fams["bert_test_ms_bucket"]
    inf_chunk = next(k for k in hb if 'le="+Inf"' in k)
    assert parse_prometheus_labels(inf_chunk)["task"] == nasty
    count_val = next(iter(fams["bert_test_ms_count"].values()))
    assert hb[inf_chunk] == count_val == 3.0
    assert next(iter(fams["bert_test_ms_sum"].values())) == \
        pytest.approx(55.5)
    # buckets are cumulative and monotone in le
    by_le = {parse_prometheus_labels(k)["le"]: v for k, v in hb.items()}
    assert by_le["1"] <= by_le["10"] <= by_le["+Inf"]


def test_parse_prometheus_labels_rejects_malformed():
    for bad in ("no_braces", '{k="unterminated}', '{k=unquoted}',
                '{="v"}'):
        with pytest.raises(ValueError):
            parse_prometheus_labels(bad)


# -- prober verifiers + injector ----------------------------------------------


def test_prober_verifier_schemas():
    good = {
        "squad": {"answer": "the cat", "nbest": [{"text": "the cat"}],
                  "n_windows": 1},
        "ner": {"labels": ["O", "B-PER", "O", "O", "O", "B-LOC"]},
        "classify": {"label": "positive",
                     "scores": {"negative": 0.25, "positive": 0.75}},
        "choice": {"choice": 1, "scores": [0.4, 0.6]},
        "embed": {"embedding": [0.6, 0.8], "dim": 2},
    }
    assert set(VERIFIERS) == set(KNOWN_ANSWER_PAYLOADS) == set(good)
    for task, out in good.items():
        payload = KNOWN_ANSWER_PAYLOADS[task]
        assert VERIFIERS[task](payload, out) is None, task
    # each verifier rejects a structurally broken answer
    assert VERIFIERS["squad"]({}, {"answer": 3, "nbest": [],
                                   "n_windows": 1})
    assert VERIFIERS["ner"]({"tokens": ["a", "b"]}, {"labels": ["O"]})
    assert VERIFIERS["classify"]({}, {"label": "x",
                                      "scores": {"x": 0.2, "y": 0.2}})
    assert VERIFIERS["choice"]({"choices": ["a", "b"]},
                               {"choice": 5, "scores": [0.5, 0.5]})
    assert VERIFIERS["embed"]({}, {"embedding": [3.0, 4.0], "dim": 2})


def test_canonicalize_detects_drift_ignores_latency():
    a = {"answer": "cat", "latency_ms": 12.3,
         "nbest": [{"p": 0.123456789}]}
    b = {"answer": "cat", "latency_ms": 99.9,
         "nbest": [{"p": 0.123456111}]}
    assert canonicalize(a) == canonicalize(b)  # volatile + 4dp rounding
    c = dict(a, answer="dog")
    assert canonicalize(a) != canonicalize(c)


def test_fault_injector_negates_and_gates_on_time():
    clock = FakeClock(0.0)
    inj = FaultInjector("corrupt_answers", after_s=5.0, time_fn=clock)
    assert not inj.active()
    clock.tick(6.0)
    assert inj.active()
    inj.force(False)
    assert not inj.active()
    inj.force(True)
    assert inj.active()
    out = _negate_tree({"a": (1.0, [2.0]), "b": 3})
    assert out == {"a": (-1.0, [-2.0]), "b": -3}
    with pytest.raises(ValueError):
        FaultInjector("nope")


# -- e2e: one live server, all five tasks, all three drills -------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode("utf-8"))


def _post(url, body, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode("utf-8"))


@pytest.fixture(scope="module")
def slo_server(serving_fixture, tmp_path_factory):
    """run_server.serve() with the SLO plane on: every registered task,
    tiny burn windows, the prober at a fast cadence, and a DORMANT
    corrupt_answers injector (arms in 99999s) the drill tests toggle
    via injector.force()/set_mode() — one warmup pays for all drills."""
    import run_server

    _msf, fixture_root, _paths = serving_fixture
    root = str(tmp_path_factory.mktemp("slo_cfg"))
    with open(os.path.join(fixture_root, "serve_args.txt"),
              encoding="utf-8") as f:
        serve_args = [ln for ln in f.read().splitlines() if ln]
    slo_cfg = {
        "windows": {"page": {"short_s": 2.0, "long_s": 8.0,
                             "burn_rate": 2.0},
                    "ticket": {"short_s": 4.0, "long_s": 16.0,
                               "burn_rate": 1.5}},
        "serve": [{"name": "availability", "kind": "availability",
                   "budget": 0.05, "min_events": 3},
                  {"name": "latency_p99", "kind": "latency",
                   "bound_ms": 10000, "budget": 0.05, "min_events": 3}],
    }
    cfg_path = os.path.join(str(root), "slo.json")
    with open(cfg_path, "w", encoding="utf-8") as f:
        json.dump(slo_cfg, f)
    args = run_server.parse_arguments(serve_args + [
        "--buckets", "32", "--batch_rows", "2", "--max_segments", "2",
        "--serve_dtype", "float32", "--packing", "on",
        "--port", "0", "--host", "127.0.0.1",
        "--slo_config", cfg_path, "--slo_eval_interval_s", "0.2",
        "--prober", "on", "--probe_interval_s", "0.25",
        "--probe_timeout_s", "10",
        "--slo_inject", "corrupt_answers", "--slo_inject_task", "squad",
        "--slo_inject_after_s", "99999"])
    handle = run_server.serve(args)
    yield handle
    handle.close()


def _wait(pred, timeout=60.0, interval=0.2, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(interval)
    pytest.fail(f"timed out waiting for {what}")


def test_e2e_clean_run_zero_alerts(slo_server):
    url = slo_server.url
    assert slo_server.prober.wait_healthy(timeout=60, min_probes=1), \
        slo_server.prober.status()
    code, hz = _get(url + "/healthz")
    assert code == 200 and hz["status"] == "ok"
    assert hz["slo"]["alerts_firing"] == 0
    assert hz["prober"]["healthy"] is True
    assert sorted(hz["prober"]["tasks"]) == sorted(hz["tasks"])
    code, alerts = _get(url + "/v1/alerts")
    assert code == 200
    assert alerts["status"] == "ok" and alerts["firing"] == []
    code, slo = _get(url + "/v1/slo")
    assert code == 200
    assert set(slo["slos"]) == {"availability", "latency_p99"}
    for s in slo["slos"].values():
        assert 0.0 <= s["budget_remaining"] <= 1.0
        assert not s["firing"]


def test_e2e_prober_known_answer_roundtrip_all_tasks(slo_server):
    # decode-verify round-trip for every registered task: the canary
    # payload admits, decodes, passes its schema verifier, and matches
    # the pinned baseline — through the real frontend
    st = slo_server.prober.status()
    assert sorted(st["tasks"]) == sorted(KNOWN_ANSWER_PAYLOADS)
    for task in KNOWN_ANSWER_PAYLOADS:
        result, detail = slo_server.prober.probe_once(task)
        assert result == "ok", (task, result, detail)
        assert st["tasks"][task]["baseline_set"], task


def test_e2e_corrupt_answers_localized_to_injected_task(slo_server):
    url = slo_server.url
    inj = slo_server.injector
    inj.set_mode("corrupt_answers")
    inj.force(True)
    try:
        _wait(lambda: slo_server.prober.status()["unhealthy_tasks"],
              what="prober to flag the corrupted task")
        st = slo_server.prober.status()
        # LOCALIZED: exactly the injected task, the other four stay ok
        assert st["unhealthy_tasks"] == ["squad"], st
        assert st["tasks"]["squad"]["last_result"] == "mismatch"
        code, hz = _get(url + "/healthz")
        assert hz["status"] == "failing"
        code, alerts = _get(url + "/v1/alerts")
        probe = [a for a in alerts["firing"]
                 if a["slo"] == "probe_squad"]
        assert probe and probe[0]["severity"] == "page", alerts["firing"]
        assert probe[0]["source"] == "prober"
        # real traffic on an uninjected task is untouched
        code, out = _post(url + "/v1/ner",
                          {"tokens": ["the", "cat", "sat"]})
        assert code == 200 and len(out["labels"]) == 3
    finally:
        inj.force(False)
    _wait(lambda: not slo_server.prober.status()["unhealthy_tasks"],
          what="probe health to recover")
    _wait(lambda: _get(url + "/healthz")[1]["status"] == "ok",
          what="status to settle ok")


def test_e2e_error_burst_pages_within_fast_window_then_resolves(slo_server):
    url = slo_server.url
    inj = slo_server.injector
    inj.set_mode("error_burst")
    inj.force(True)
    try:
        def burst_and_check():
            _post(url + "/v1/ner", {"tokens": ["the", "cat", "sat"]})
            _, alerts = _get(url + "/v1/alerts")
            return any(a["slo"] == "availability"
                       and a["severity"] == "page"
                       for a in alerts["firing"])

        _wait(burst_and_check, interval=0.1,
              what="availability page alert under error_burst")
        code, hz = _get(url + "/healthz")
        assert hz["status"] == "failing"
        assert "availability:page" in hz["slo"]["firing"]
    finally:
        inj.force(False)

    def clean_and_check():
        _post(url + "/v1/ner", {"tokens": ["the", "cat", "sat"]})
        _, alerts = _get(url + "/v1/alerts")
        return not any(a["slo"] == "availability"
                       for a in alerts["firing"])

    _wait(clean_and_check, what="availability alert to resolve")
    _, alerts = _get(url + "/v1/alerts")
    assert any(a["slo"] == "availability" for a in alerts["resolved"])
    _wait(lambda: _get(url + "/healthz")[1]["status"] == "ok",
          what="status to settle ok")
