"""Native (C++) byte-level BPE encoder: id-exact parity with the Python spec
in data/tokenization.py (ByteLevelBPETokenizer), factory auto-selection, and
a measured speedup (SURVEY §2.3#7 — the half of the reference's Rust
`tokenizers` role that the WordPiece library didn't cover:
reference src/tokenization.py:51-57)."""

import json
import random
import time

import pytest

from bert_pytorch_tpu.data.tokenization import (
    ByteLevelBPETokenizer,
    bytes_to_unicode,
    get_bpe_tokenizer,
)

native = pytest.importorskip("bert_pytorch_tpu.native")
if not native.native_bpe_available():
    pytest.skip("native BPE library not buildable here",
                allow_module_level=True)


def _tiny_bpe():
    """Small but real vocab/merges: all 256 byte symbols + common merges."""
    byte_syms = list(bytes_to_unicode().values())
    merges = [
        ("Ġ", "t"), ("Ġt", "h"), ("Ġth", "e"), ("h", "e"), ("i", "n"),
        ("e", "r"), ("Ġ", "a"), ("r", "e"), ("o", "n"), ("Ġa", "n"),
        ("e", "n"), ("Ġ", "s"), ("a", "t"), ("o", "r"), ("Ġ", "w"),
        ("n", "d"), ("Ġan", "d"), ("o", "u"), ("in", "g"), ("1", "2"),
        ("12", "3"),
    ]
    vocab = {"<unk>": 0, "<s>": 1, "</s>": 2}
    for s in byte_syms:
        if s not in vocab:
            vocab[s] = len(vocab)
    for a, b in merges:
        tok = a + b
        if tok not in vocab:
            vocab[tok] = len(vocab)
    return vocab, merges


CURATED = [
    "The quick brown fox jumped over the lazy dog.",
    "it's we're I'll you've don't I'm he'd",
    "Café CAFÉ café 你好 world",
    "  weird\tspacing and​ stuff ",
    "numbers 123 and 456.789",
    "", " ", "   ", "!!!", "'", "''",
    "mixed'case O'Brien's",
    "a\x00b � c",
    "İstanbul İ",  # Turkish dotted capital I (1->2 lowering)
    "tab\t\tnewline\n\ndone",
]


@pytest.fixture(scope="module")
def both():
    vocab, merges = _tiny_bpe()
    return (ByteLevelBPETokenizer(vocab, merges),
            native.NativeByteLevelBPETokenizer(vocab, merges))


def test_curated_parity(both):
    py, nat = both
    for txt in CURATED:
        assert py.encode(txt).ids == nat.encode(txt).ids, repr(txt)


def test_lowercase_parity():
    vocab, merges = _tiny_bpe()
    py = ByteLevelBPETokenizer(vocab, merges, lowercase=True)
    nat = native.NativeByteLevelBPETokenizer(vocab, merges, lowercase=True)
    for txt in CURATED:
        assert py.encode(txt).ids == nat.encode(txt).ids, repr(txt)


GREEK = [
    "ΟΔΟΣ",          # final sigma at word end (Σ -> ς)
    "ΟΔΟΣ ΟΔΟΣ.",    # word-end before space / punctuation
    "ΣΟΦΙΑ",          # sigma word-initial (stays σ)
    "Σ", "ΟΣ'", "Σ'Σ",  # apostrophe is case-ignorable: context skips it
    "ΑΣ́Β",      # combining acute (case-ignorable) between cased
    "abcΣ", "Σabc", "1Σ2",
]


def test_final_sigma_parity():
    """str.lower()'s one context-sensitive rule (Greek Final_Sigma) must
    survive the C++ port — the per-codepoint map alone gets this wrong."""
    vocab, merges = _tiny_bpe()
    py = ByteLevelBPETokenizer(vocab, merges, lowercase=True)
    nat = native.NativeByteLevelBPETokenizer(vocab, merges, lowercase=True)
    for txt in GREEK:
        assert py.encode(txt).ids == nat.encode(txt).ids, repr(txt)


def test_gapped_vocab_ids_survive():
    """A filtered/hand-edited vocab with non-contiguous ids must keep its
    exact ids through the native path (id-aware serialization)."""
    vocab, merges = _tiny_bpe()
    vocab["zz"] = 500  # gap: ids jump from ~280 to 500
    merges = list(merges) + [("z", "z")]
    py = ByteLevelBPETokenizer(vocab, merges)
    nat = native.NativeByteLevelBPETokenizer(vocab, merges)
    enc_py, enc_nat = py.encode("fizz buzz"), nat.encode("fizz buzz")
    assert 500 in enc_nat.ids
    assert enc_py.ids == enc_nat.ids
    assert enc_py.tokens == enc_nat.tokens


def test_oov_piece_falls_back_to_python():
    """When a piece is missing from the vocab, the spec keeps the raw piece
    string in tokens and maps the id to unk; the native path must match
    (it re-encodes such rows through Python)."""
    vocab, merges = _tiny_bpe()
    vocab.pop("X")  # knock a byte symbol out of the vocab
    py = ByteLevelBPETokenizer(vocab, merges)
    nat = native.NativeByteLevelBPETokenizer(vocab, merges)
    enc_py, enc_nat = py.encode("aXb"), nat.encode("aXb")
    assert enc_py.ids == enc_nat.ids
    assert enc_py.tokens == enc_nat.tokens  # raw 'X' piece, not '<unk>'
    batch = nat.encode_batch(["aXb", "ab"])
    assert batch[0].ids == enc_py.ids
    assert batch[0].tokens == enc_py.tokens
    lens, ids = nat.encode_batch_arrays(["aXb", "ab"])
    assert ids[:lens[0]].tolist() == enc_py.ids


def test_fuzz_parity(both):
    py, nat = both
    rng = random.Random(0)
    alphabet = ("abcdefghijklmnopqrstuvwxyz ABC   '\t\n.,!?0123456789"
                "éÉ你好İı​�")
    for _ in range(300):
        txt = "".join(rng.choice(alphabet)
                      for _ in range(rng.randrange(0, 80)))
        assert py.encode(txt).ids == nat.encode(txt).ids, repr(txt)


def test_batch_matches_single(both):
    _, nat = both
    texts = CURATED * 3
    batch = nat.encode_batch(texts, nthreads=4)
    for txt, enc in zip(texts, batch):
        assert enc.ids == nat.encode(txt).ids


def test_encode_batch_arrays(both):
    _, nat = both
    texts = ["the cat sat", "", "and 123 dogs"]
    lens, ids = nat.encode_batch_arrays(texts)
    assert lens.sum() == len(ids)
    off = 0
    for txt, ln in zip(texts, lens):
        assert ids[off:off + ln].tolist() == nat.encode(txt).ids
        off += ln


def test_factory_prefers_native(tmp_path):
    vocab, merges = _tiny_bpe()
    vpath = tmp_path / "vocab.json"
    vpath.write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text(
        "\n".join(f"{a} {b}" for a, b in merges))
    tok = get_bpe_tokenizer(str(vpath))
    assert isinstance(tok, native.NativeByteLevelBPETokenizer)


def test_speedup(both):
    py, nat = both
    texts = [("the quick brown fox jumped over the lazy dog and "
              "ran in circles 123 times, singing' songs. ") * 6] * 200
    t0 = time.time()
    for t in texts[:50]:
        py.encode(t)
    py_rate = 50 / (time.time() - t0)
    t0 = time.time()
    nat.encode_batch(texts, nthreads=4)
    nat_rate = len(texts) / (time.time() - t0)
    # conservative bound; single-core native alone is several x
    assert nat_rate > 2 * py_rate, (py_rate, nat_rate)
