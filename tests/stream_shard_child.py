"""Child process for the two-process streaming shard test.

Builds a StreamingPretrainingLoader for one (rank, world_size) — NO jax,
the streaming plane is plain host Python — drains it, and dumps which
corpus documents this rank consumed plus a per-batch content digest.

The test corpus encodes each document's global index in its own token
stream (tests/test_streaming.py doc_words), so the parent can recover
record ownership from batch CONTENT alone: disjointness is proven on what
was actually trained on, not on the enumeration arithmetic repeating
itself.

Usage: python stream_shard_child.py CORPUS_DIR VOCAB RANK WORLD OUT_JSON
"""

import hashlib
import json
import sys

import numpy as np

HERE = __file__.rsplit("/", 1)[0]
sys.path.insert(0, HERE + "/..")

from bert_pytorch_tpu.data.streaming import (  # noqa: E402
    StreamingPretrainingLoader, discover_sources)
from bert_pytorch_tpu.data.tokenization import (  # noqa: E402
    BertWordPieceTokenizer, load_vocab)


def main() -> None:
    corpus_dir, vocab_path, rank, world, out_json = sys.argv[1:6]
    vocab = load_vocab(vocab_path)
    tok = BertWordPieceTokenizer(vocab)
    n_specials = 5  # [PAD] [UNK] [CLS] [SEP] [MASK] lead the vocab
    n_words = len(vocab) - n_specials

    loader = StreamingPretrainingLoader(
        discover_sources(corpus_dir), tok, batch_size=4, seq_len=16,
        mask_token_index=4, max_pred_per_seq=3, masked_lm_prob=0.15,
        vocab_size=len(vocab), seed=7, world_size=int(world),
        rank=int(rank), num_workers=2, prefetch_batches=2)

    docs = set()
    digests = []
    for batch in loader:
        # reconstruct the unmasked stream, then decode the doc index the
        # corpus embeds as the first two word tokens after [CLS]
        orig = np.where(batch["masked_lm_labels"] != -1,
                        batch["masked_lm_labels"], batch["input_ids"])
        for row in orig:
            hi, lo = int(row[1]) - n_specials, int(row[2]) - n_specials
            docs.add(hi * n_words + lo)
        digests.append(hashlib.sha256(orig.tobytes()).hexdigest())
    loader.close()

    with open(out_json, "w", encoding="utf-8") as f:
        json.dump({"rank": int(rank), "docs": sorted(docs),
                   "digests": digests}, f)


if __name__ == "__main__":
    main()
