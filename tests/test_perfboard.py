"""tools/perfboard.py: the cross-run perf index and regression gate.

The acceptance round-trip: a synthetic BENCH json goes through index ->
check -> regression detection; a 15% MFU regression exits nonzero naming
the metric, a within-tolerance drift exits zero; results/runs.jsonl +
RUNS.md regenerate deterministically from the checked-in artifacts; and
scripts/check_perf.sh gates the newest two MULTICHIP artifacts. All
jax-free by construction (perfboard must run on a login host / in CI)."""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from tools.perfboard import (  # noqa: E402
    bench_metrics, check_artifacts, extract, finetune_metrics,
    index_records, main as pb_main, metric_direction, multichip_metrics,
    render_markdown, runlog_metrics, serve_metrics)


def _bench_artifact(path, value, mfu, rc=0):
    path.write_text(json.dumps({
        "n": 9, "rc": rc,
        "parsed": {"metric": "bert_large_mlm_seq128_train_throughput",
                   "value": value, "unit": "seq/s/chip",
                   "vs_baseline": round(value / 376.5, 4),
                   "seq512_value": value / 5.6, "seq512_mfu": mfu},
    }))
    return str(path)


# -- extraction ---------------------------------------------------------------

def test_bench_extraction_real_artifact():
    with open(os.path.join(REPO, "BENCH_r05.json")) as f:
        m = bench_metrics(json.load(f))
    assert m["seq128_seq_per_sec_per_chip"] == 546.17
    assert m["seq512_mfu"] == 0.5073


def test_bench_extraction_tolerates_null_parsed():
    # BENCH_r04.json shipped with parsed: null — index, don't crash
    assert bench_metrics({"rc": 0, "parsed": None}) == {}


def test_multichip_extraction_real_artifact():
    with open(os.path.join(REPO, "MULTICHIP_r07.json")) as f:
        m = multichip_metrics(json.load(f))
    assert m["dp.scaling_efficiency"] == 0.1448
    assert m["dp_zero1_overlap.scaling_efficiency"] == 0.2206
    assert m["zero1_overlap_step_time_ratio_vs_zero1"] == 0.5995


def test_metric_directions():
    assert metric_direction("seq512_mfu") == "higher"
    assert metric_direction("dp.scaling_efficiency") == "higher"
    assert metric_direction("data_wait_ms_median") == "lower"
    assert metric_direction("dp.step_time_ms") is None       # index-only
    assert metric_direction("zero1_step_time_ratio_vs_dp") is None
    # runlog shapes: absolute step time stays index-only under the
    # _median suffix, and run-length bookkeeping is never a perf gate
    assert metric_direction("step_time_ms_median") is None
    assert metric_direction("last_step") is None
    assert metric_direction("perf_intervals") is None
    assert metric_direction("seq_per_sec_median") == "higher"


def test_check_runlogs_faster_steps_is_not_a_regression(tmp_path):
    """A run whose median step time IMPROVED must pass the gate (it used
    to be gated higher-is-better and exit 1 on the improvement)."""

    def runlog(path, stms, n=3):
        with open(path, "w") as f:
            for i in range(n):
                f.write(json.dumps({"tag": "perf", "step": 10 * (i + 1),
                                    "step_time_ms": stms,
                                    "seq_per_sec": 6400.0 / stms}) + "\n")
        return str(path)

    base = runlog(tmp_path / "base.jsonl", 120.0)
    fast = runlog(tmp_path / "fast.jsonl", 90.0, n=2)  # fewer intervals too
    regressions, _ = check_artifacts(base, fast, tolerance=0.1)
    assert regressions == []
    # ...and a genuine slowdown is caught through the gated seq/s view
    slow = runlog(tmp_path / "slow.jsonl", 240.0)
    regressions, _ = check_artifacts(base, slow, tolerance=0.1)
    assert any("seq_per_sec_median" in r for r in regressions)
    assert not any("step_time_ms_median" in r for r in regressions)


def test_runlog_extraction(tmp_path):
    log = tmp_path / "run.jsonl"
    with open(log, "w") as f:
        f.write(json.dumps({"tag": "header", "git_sha": "abc"}) + "\n")
        for step, stms in ((10, 100.0), (20, 120.0), (30, 110.0)):
            f.write(json.dumps({
                "tag": "perf", "step": step, "step_time_ms": stms,
                "seq_per_sec": 8.0, "mfu": 0.4,
                "packing_efficiency": 0.9}) + "\n")
        f.write(json.dumps({"tag": "train", "step": 30, "loss": 2.0})
                + "\n")
    m = runlog_metrics(str(log))
    assert m["perf_intervals"] == 3
    assert m["last_step"] == 30
    assert m["step_time_ms_median"] == 110.0
    assert m["packing_efficiency"] == 0.9
    assert runlog_metrics(str(tmp_path / "missing.jsonl")) == {}


# -- the regression gate ------------------------------------------------------

def test_check_flags_15pct_mfu_regression_and_names_it(tmp_path):
    base = _bench_artifact(tmp_path / "baseline.json", 500.0, 0.50)
    cur = _bench_artifact(tmp_path / "current.json", 495.0, 0.425)
    regressions, _ = check_artifacts(base, cur, tolerance=0.1)
    assert len(regressions) == 1
    assert "seq512_mfu" in regressions[0]
    assert "0.425" in regressions[0]
    # CLI exit code 1, naming the metric on stdout
    rc = pb_main(["--check", base, cur, "--tolerance", "0.1"])
    assert rc == 1


def test_check_passes_within_tolerance(tmp_path):
    base = _bench_artifact(tmp_path / "baseline.json", 500.0, 0.50)
    cur = _bench_artifact(tmp_path / "current.json", 480.0, 0.48)  # -4%
    regressions, notes = check_artifacts(base, cur, tolerance=0.1)
    assert regressions == []
    assert any("seq512_mfu" in n for n in notes)
    assert pb_main(["--check", base, cur, "--tolerance", "0.1"]) == 0


def test_check_improvement_never_fails(tmp_path):
    base = _bench_artifact(tmp_path / "baseline.json", 500.0, 0.50)
    cur = _bench_artifact(tmp_path / "current.json", 900.0, 0.95)
    regressions, _ = check_artifacts(base, cur, tolerance=0.1)
    assert regressions == []


def test_check_missing_metric_notes_but_passes(tmp_path):
    base = _bench_artifact(tmp_path / "baseline.json", 500.0, 0.50)
    cur = tmp_path / "current.json"
    cur.write_text(json.dumps({"rc": 0, "parsed": {"value": 505.0}}))
    regressions, notes = check_artifacts(base, str(cur), tolerance=0.1)
    assert regressions == []
    assert any(n.startswith("MISSING") and "seq512_mfu" in n
               for n in notes)


def test_check_refuses_cross_kind_and_empty(tmp_path):
    bench = _bench_artifact(tmp_path / "b.json", 500.0, 0.5)
    mc = tmp_path / "MULTICHIP_x.json"
    mc.write_text(json.dumps({"variants": {
        "dp": {"scaling_efficiency": 0.2}}}))
    with pytest.raises(SystemExit, match="kinds differ"):
        check_artifacts(bench, str(mc), 0.1)
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    with pytest.raises(SystemExit, match="no comparable"):
        check_artifacts(str(empty), bench, 0.1)


def test_check_multichip_variant_regression(tmp_path):
    def mc(path, eff):
        path.write_text(json.dumps({"variants": {
            "dp": {"scaling_efficiency": eff, "seqs_per_sec": eff * 200,
                   "step_time_ms": 100.0 / eff}}}))
        return str(path)

    base = mc(tmp_path / "MULTICHIP_a.json", 0.20)
    cur = mc(tmp_path / "MULTICHIP_b.json", 0.12)
    regressions, _ = check_artifacts(base, cur, tolerance=0.25)
    names = "\n".join(regressions)
    assert "dp.scaling_efficiency" in names
    assert "dp.seqs_per_sec" in names
    assert "step_time_ms" not in names  # index-only, never gated


# -- the index ----------------------------------------------------------------

def test_index_regenerates_deterministically(tmp_path):
    out1, md1 = tmp_path / "runs1.jsonl", tmp_path / "RUNS1.md"
    out2, md2 = tmp_path / "runs2.jsonl", tmp_path / "RUNS2.md"
    assert pb_main(["--root", REPO, "--out", str(out1),
                    "--md", str(md1)]) == 0
    assert pb_main(["--root", REPO, "--out", str(out2),
                    "--md", str(md2)]) == 0
    assert out1.read_bytes() == out2.read_bytes()
    assert md1.read_bytes() == md2.read_bytes()
    # ...and the checked-in board matches what the checked-in artifacts
    # produce (regenerate via `python tools/perfboard.py` after adding a
    # BENCH/MULTICHIP artifact)
    assert out1.read_bytes() == (
        open(os.path.join(REPO, "results", "runs.jsonl"), "rb").read())
    assert md1.read_bytes() == (
        open(os.path.join(REPO, "RUNS.md"), "rb").read())


def test_index_contents_cover_all_rounds():
    records = index_records(REPO)
    bench = [r for r in records if r["kind"] == "bench"]
    mc = [r for r in records if r["kind"] == "multichip"]
    assert [r["round"] for r in bench] == [1, 2, 3, 4, 5, 6]
    assert [r["round"] for r in mc] == [1, 2, 3, 4, 5, 6, 7, 8, 9]
    r07 = next(r for r in mc if r["round"] == 7)
    assert r07["measured"] and r07["ok"]
    assert r07["metrics"]["dp_zero1_overlap.scaling_efficiency"] == 0.2206
    # round 16: the streaming input-plane pair (scripts/input_bench.py
    # --stream) rides the bench board — tokens/s higher-better, the paced
    # starvation fraction lower-better via the data_wait marker
    r06 = next(r for r in bench if r["round"] == 6)
    assert r06["measured"] and r06["ok"]
    assert r06["metrics"]["stream.tokens_per_sec"] > 0
    assert 0.0 <= r06["metrics"]["stream.data_wait_fraction"] <= 1.0
    from tools.perfboard import metric_direction

    assert metric_direction("stream.tokens_per_sec") == "higher"
    assert metric_direction("stream.data_wait_fraction") == "lower"
    # failed artifacts indexed honestly, not dropped
    r01 = next(r for r in mc if r["round"] == 1)
    assert not r01["ok"] and not r01["measured"]


def test_finetune_extraction_real_artifact_and_gate_directions():
    """FINETUNE_r01.json (run_finetune.py --perf_artifact across all
    five registered tasks) indexes with per-task real_tokens_per_sec /
    pad_fraction, direction-aware: throughput higher-better, pad
    fraction lower-better, absolute step time index-only."""
    kind, metrics, raw = extract(os.path.join(REPO, "FINETUNE_r01.json"))
    assert kind == "finetune"
    for task in ("squad", "ner", "classify", "choice", "embed"):
        assert metrics[f"{task}.real_tokens_per_sec"] > 0, task
        assert 0.0 <= metrics[f"{task}.pad_fraction"] < 1.0, task
    assert metric_direction("classify.real_tokens_per_sec") == "higher"
    assert metric_direction("classify.pad_fraction") == "lower"
    assert metric_direction("classify.step_time_ms") is None
    # regression gate catches a pad-fraction blowup on the same kind
    worse = {"kind": "finetune",
             "tasks": {t: dict(raw["tasks"][t]) for t in raw["tasks"]}}
    worse["tasks"]["classify"]["pad_fraction"] = min(
        0.99, raw["tasks"]["classify"]["pad_fraction"] * 2 + 0.1)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        cur = os.path.join(d, "FINETUNE_r02.json")
        with open(cur, "w") as f:
            json.dump(worse, f)
        regressions, _notes = check_artifacts(
            os.path.join(REPO, "FINETUNE_r01.json"), cur, 0.1)
    assert any("classify.pad_fraction" in r for r in regressions)
    # the table renders a finetune section
    md = render_markdown(index_records(REPO))
    assert "## Finetune" in md and "classify" in md
    assert finetune_metrics({"tasks": {"x": {"mfu": None}}}) == {}


def test_index_tolerates_artifact_without_round_suffix(tmp_path):
    """A BENCH_baseline.json (no _rN suffix) must index and render under
    its filename, not crash the whole board on round=None."""
    root = tmp_path / "root"
    root.mkdir()
    _bench_artifact(root / "BENCH_baseline.json", 400.0, 0.40)
    _bench_artifact(root / "BENCH_r01.json", 500.0, 0.50)
    out, md = tmp_path / "runs.jsonl", tmp_path / "RUNS.md"
    assert pb_main(["--root", str(root), "--out", str(out),
                    "--md", str(md)]) == 0
    text = md.read_text()
    assert "BENCH_baseline.json" in text and "r01" in text
    records = [json.loads(l) for l in out.read_text().splitlines()]
    assert {r["artifact"]: r["round"] for r in records} == {
        "BENCH_baseline.json": None, "BENCH_r01.json": 1}


def test_markdown_renders_runlog_section(tmp_path):
    log = tmp_path / "phase1.jsonl"
    with open(log, "w") as f:
        f.write(json.dumps({"tag": "perf", "step": 4,
                            "step_time_ms": 50.0, "mfu": 0.3}) + "\n")
    records = index_records(REPO, runs=[str(log)])
    md = render_markdown(records)
    assert "## Run logs" in md
    assert "phase1.jsonl" in md


def test_serve_metrics_gate_restricts_latency_to_sustained_sampled_rates():
    """The gate's view (for_check=True) of a SERVE artifact drops latency
    percentiles past the saturation knee (open-loop overload measures
    divergent queueing, not the binary) and where the 2xx sample count
    can't support the order statistic (p95 < 100, p99 < 200 samples).
    Throughput/cost keys stay gated at every rate, and the indexing view
    (default) keeps everything."""
    rec = {"p50_ms": 10.0, "p95_ms": 20.0, "p99_ms": 30.0,
           "req_per_sec": 9.0, "batch_occupancy": 0.5,
           "cost_per_1k_tokens": 1e-4}
    doc = {"kind": "serve", "modes": {"m": {
        "saturation": {"at_rate": 20.0, "req_per_sec": 19.0},
        "rates": {
            "10": dict(rec, n_2xx=300),      # sustained, well sampled
            "20": dict(rec, n_2xx=150),      # at the knee, p99-starved
            "40": dict(rec, n_2xx=5000),     # past the knee: overloaded
        }}}}
    idx = serve_metrics(doc)
    gate = serve_metrics(doc, for_check=True)
    # sustained + >=200 samples: all three percentiles survive the gate
    for k in ("p50_ms", "p95_ms", "p99_ms"):
        assert f"m.r10.{k}" in gate
    # 150 samples clears the p95 floor (100) but not the p99 floor (200)
    assert "m.r20.p95_ms" in gate and "m.r20.p99_ms" not in gate
    assert "m.r20.p50_ms" in gate
    # past-knee percentiles are never gated, however well sampled
    for k in ("p50_ms", "p95_ms", "p99_ms"):
        assert f"m.r40.{k}" not in gate
    # throughput, occupancy and cost gate at every rate incl. overload
    for r in ("10", "20", "40"):
        for k in ("req_per_sec", "batch_occupancy", "cost_per_1k_tokens"):
            assert f"m.r{r}.{k}" in gate
    # the knee itself stays gated — a genuine slowdown still trips it
    assert "m.saturation.req_per_sec" in gate
    # indexing keeps every per-rate key the artifact carries
    for r in ("10", "20", "40"):
        for k in rec:
            assert f"m.r{r}.{k}" in idx
    assert set(idx) >= set(gate)


def test_check_artifacts_ignores_overload_latency_but_gates_knee(tmp_path):
    """End-to-end through check_artifacts: a 4x past-knee p99 swing (the
    measured run-to-run noise of the CPU harness) does not flag, while a
    saturation-throughput drop beyond tolerance does."""
    def art(p99_overload, knee_rps):
        return {"kind": "serve", "modes": {"m": {
            "saturation": {"at_rate": 20.0, "req_per_sec": knee_rps},
            "rates": {
                "10": {"p99_ms": 25.0, "req_per_sec": 9.0, "n_2xx": 300},
                "40": {"p99_ms": p99_overload, "req_per_sec": 18.0,
                       "n_2xx": 300},
            }}}}
    base = tmp_path / "SERVE_r01.json"
    cur = tmp_path / "SERVE_r02.json"
    base.write_text(json.dumps(art(200.0, 19.0)))
    cur.write_text(json.dumps(art(800.0, 19.0)))
    regressions, _ = check_artifacts(str(base), str(cur), 0.6)
    assert regressions == []
    cur.write_text(json.dumps(art(200.0, 5.0)))
    regressions, _ = check_artifacts(str(base), str(cur), 0.6)
    assert any("saturation.req_per_sec" in r for r in regressions)


# -- the shell gate -----------------------------------------------------------

def test_check_perf_sh_gates_newest_two_multichip():
    """scripts/check_perf.sh exits 0 on the checked-in artifact pair (the
    r08->r09 wall-clock/efficiency noise is documented and inside the
    CPU-harness tolerance — incl. the compounded single-vs-sweep drift
    the round-15 default tolerance is sized to) and nonzero when handed
    a strict tolerance that the known cross-session noise must trip."""
    script = os.path.join(REPO, "scripts", "check_perf.sh")
    r = subprocess.run(["bash", script], capture_output=True, text=True,
                       cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MULTICHIP_r08.json -> MULTICHIP_r09.json" in r.stdout
    r_strict = subprocess.run(["bash", script, "0.05"],
                              capture_output=True, text=True, cwd=REPO)
    assert r_strict.returncode == 1, r_strict.stdout + r_strict.stderr
    assert "REGRESSION" in r_strict.stdout
