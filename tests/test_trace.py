"""telemetry/trace.py interval semantics: per-(pid,tid) merging of
overlapping events, zero-duration events, out-of-order completion, and
the crashed-run contract — unmatched B/b begins close at the trace end
with a `truncated` flag instead of raising or silently dropping."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.telemetry.trace import (  # noqa: E402
    _merged_total_us, classify, summarize_events)


def X(name, ts, dur, pid=1, tid=1):
    return {"ph": "X", "name": name, "ts": ts, "dur": dur,
            "pid": pid, "tid": tid}


# -- interval merge core ------------------------------------------------------

def test_merged_total_overlap_containment_and_disjoint():
    # [0,10) + [5,15) overlap -> 15; [20,30) disjoint -> +10;
    # [21,25) contained -> +0
    assert _merged_total_us([(0, 10), (5, 15), (20, 30), (21, 25)]) == 25


def test_merged_total_out_of_order_input():
    # completion order != start order: sort inside the merge handles it
    assert _merged_total_us([(20, 30), (0, 10), (5, 15)]) == 25


def test_merged_total_zero_duration():
    assert _merged_total_us([(5, 5), (5, 5), (7, 7)]) == 0
    assert _merged_total_us([]) == 0


# -- same-(pid,tid) overlapping events ---------------------------------------

def test_overlapping_same_thread_events_merge_not_sum():
    """A wrapper op re-reporting a nested op on the SAME thread must not
    double-count; the same ops on ANOTHER thread must sum."""
    events = [
        X("all-gather-start.1", 0, 100),
        X("all-gather-start.2", 50, 100),          # overlaps on tid 1
        X("all-gather-start.3", 0, 100, tid=2),    # concurrent on tid 2
    ]
    s = summarize_events(events)
    assert s["collective_ms"] == (150 + 100) / 1e3
    assert s["collective_by_op_ms"]["all-gather"] == 0.25
    assert s["events_classified"] == 3


def test_zero_duration_events_counted_but_costless():
    s = summarize_events([X("fusion.1", 10, 0), X("dot.1", 10, 5)])
    assert s["compute_ms"] == 0.005
    assert s["events_classified"] == 2
    assert "truncated" not in s


def test_out_of_order_completion_across_async_pairs():
    """Two async ops on one pid where the second-started finishes first
    (id-keyed matching, not stack order)."""
    events = [
        {"ph": "b", "name": "all-gather.1", "ts": 0, "pid": 1, "id": "a"},
        {"ph": "b", "name": "all-reduce.1", "ts": 10, "pid": 1, "id": "b"},
        {"ph": "e", "name": "all-reduce.1", "ts": 20, "pid": 1, "id": "b"},
        {"ph": "e", "name": "all-gather.1", "ts": 40, "pid": 1, "id": "a"},
    ]
    s = summarize_events(events)
    assert s["collective_by_op_ms"]["all-gather"] == 0.04
    assert s["collective_by_op_ms"]["all-reduce"] == 0.01
    assert "truncated" not in s


# -- truncated traces (crashed run mid-interval) ------------------------------

def test_unmatched_async_start_closes_at_trace_end_with_flag():
    """The op still open when the run died is the one the postmortem
    wants: close it at the trace end, flag the summary as truncated."""
    events = [
        X("dot.1", 0, 100),
        {"ph": "b", "name": "all-gather-start.7", "ts": 20, "pid": 1,
         "id": "g"},
        X("fusion.2", 100, 400),  # extends the trace end to 500
        # no matching 'e': the run crashed mid-collective
    ]
    s = summarize_events(events)
    assert s["truncated"] is True
    assert s["truncated_intervals"] == 1
    # closed at max_ts=500: [20, 500) -> 480 us
    assert s["collective_ms"] == 0.48
    assert s["collective_by_op_ms"]["all-gather"] == 0.48


def test_truncated_async_interval_merges_with_same_thread_ops():
    """The closed-at-end interval must land under the begin event's
    (pid, tid) so it interval-merges with that thread's completed ops —
    keying it under a synthetic thread would double-count the overlap in
    exactly the crashed-run summary truncation exists for."""
    events = [
        X("all-reduce.9", 0, 100, pid=1, tid=5),
        {"ph": "b", "name": "all-gather.2", "ts": 50, "pid": 1, "tid": 5,
         "id": "g"},
        # trace ends at 100; the open all-gather closes at [50, 100)
    ]
    s = summarize_events(events)
    assert s["truncated_intervals"] == 1
    # merged on tid 5: union of [0,100) and [50,100) is 100 us, not 150
    assert s["collective_ms"] == 0.1


def test_async_close_uses_begin_tid():
    """b/e pairs whose end event lost its tid still attribute to the
    begin's thread (the tid rides in the open-async entry)."""
    events = [
        X("all-to-all.1", 0, 40, pid=1, tid=3),
        {"ph": "b", "name": "all-to-all.2", "ts": 10, "pid": 1, "tid": 3,
         "id": "q"},
        {"ph": "e", "name": "all-to-all.2", "ts": 60, "pid": 1, "id": "q"},
    ]
    s = summarize_events(events)
    # same thread: [0,40) U [10,60) = 60 us merged, not 90 summed
    assert s["collective_ms"] == 0.06


def test_unmatched_sync_begin_closes_at_trace_end():
    events = [
        {"ph": "B", "name": "host/dispatch", "ts": 0, "pid": 9, "tid": 9},
        X("dot.3", 100, 100, pid=1, tid=1),
        # host/dispatch never Ends: the host thread was killed mid-step
    ]
    s = summarize_events(events)
    assert s["truncated"] is True
    assert s["host_ms"]["dispatch"] == 0.2  # [0, 200)


def test_matched_b_e_pairs_and_unmatched_end_ignored():
    """B/E pairs attribute like X events; an E whose B predates the
    capture window has no start to attribute and must not raise."""
    events = [
        {"ph": "E", "name": "host/h2d", "ts": 5, "pid": 1, "tid": 1},
        {"ph": "B", "name": "all-reduce.1", "ts": 10, "pid": 1, "tid": 1},
        {"ph": "E", "name": "all-reduce.1", "ts": 30, "pid": 1, "tid": 1},
    ]
    s = summarize_events(events)
    assert s["collective_ms"] == 0.02
    assert "truncated" not in s


def test_unmatched_framework_noise_not_counted_as_truncated():
    """An unmatched begin whose name classifies as framework noise is
    excluded from the totals AND from the truncation count."""
    events = [
        {"ph": "B", "name": "ThunkExecutor::Run", "ts": 0, "pid": 1,
         "tid": 1},
        X("dot.1", 0, 10),
    ]
    s = summarize_events(events)
    assert "truncated" not in s
    assert s["events_classified"] == 1


def test_classify_contract_unchanged():
    assert classify("all-gather-start.12") == "collective"
    assert classify("reduce-scatter.1") == "collective"
    assert classify("transpose_copy_fusion") == "compute"
    assert classify("host/data_wait") == "host/data_wait"
    assert classify("ThunkExecutor::Run") is None
    assert classify("PjitFunction(train_step)") is None


def test_trace_summary_cli_reports_truncation(tmp_path, capsys):
    """tools/trace_summary.py surfaces the truncation loudly instead of
    presenting a crashed trace as a complete one."""
    import gzip
    import json as _json

    from tools.trace_summary import main as ts_main

    trace = {"traceEvents": [
        X("dot.1", 0, 100),
        {"ph": "b", "name": "all-gather.1", "ts": 50, "pid": 1, "id": "x"},
    ]}
    path = tmp_path / "t.trace.json.gz"
    with gzip.open(path, "wt", encoding="utf-8") as f:
        _json.dump(trace, f)
    out_json = tmp_path / "s.json"
    summary = ts_main(["--trace", str(path), "--json", str(out_json)])
    assert summary["truncated"] is True
    assert "never completed" in capsys.readouterr().out
    assert _json.loads(out_json.read_text())["truncated_intervals"] == 1


# -- the per-KIND collective split (round 15) ---------------------------------

def test_collective_kind_split():
    """collective_kind_ms buckets every collective root into its class —
    all-gather / all-reduce / reduce-scatter / collective-permute /
    all-to-all, everything else under 'other' — with the same per-thread
    interval merge as the totals, so MULTICHIP breakdowns can say WHICH
    collective class a variant pays for."""
    from bert_pytorch_tpu.telemetry.trace import collective_kind

    assert collective_kind("all-gather") == "all-gather"
    assert collective_kind("reduce-scatter") == "reduce-scatter"
    assert collective_kind("send") == "other"
    events = [
        X("all-gather-start.1", 0, 100),
        X("all-gather-done.1", 100, 20),      # same class, same thread
        X("all-reduce.7", 0, 50),
        X("collective-permute-start.2", 200, 30),
        X("all-to-all.1", 300, 10),
        X("partition-id.1", 400, 5),          # -> other
        X("dot.1", 500, 40),                  # compute: not in the split
    ]
    s = summarize_events(events, steps=1, n_devices=1)
    kinds = s["collective_kind_ms"]
    assert kinds["all-gather"] == 0.12
    assert kinds["all-reduce"] == 0.05
    assert kinds["collective-permute"] == 0.03
    assert kinds["all-to-all"] == 0.01
    assert kinds["other"] == 0.005
    assert "reduce-scatter" not in kinds       # absent kinds are omitted
    assert s["collective_kind_ms_per_step_device"]["all-gather"] == 0.12
    # classes overlapping in time are each fully attributed (the one
    # collective total merges the overlap away — kinds may sum past it);
    # with NO cross-class overlap the split decomposes the total exactly
    disjoint = [X("all-gather.1", 0, 10), X("all-reduce.1", 20, 10)]
    s2 = summarize_events(disjoint)
    assert abs(sum(s2["collective_kind_ms"].values())
               - s2["collective_ms"]) < 1e-9


def test_collective_kind_split_merges_within_class():
    """Two overlapping roots of the SAME class on one thread merge (no
    double-count), while different classes overlap freely — each class
    reports its own merged time."""
    events = [
        X("all-gather-start.1", 0, 100),
        X("all-gather-start.2", 50, 100),     # overlap: class total 150
        X("all-reduce.1", 0, 100),            # different class, same span
    ]
    s = summarize_events(events)
    kinds = s["collective_kind_ms"]
    assert kinds["all-gather"] == 0.15
    assert kinds["all-reduce"] == 0.1
    # cross-class overlap merges away in the one collective total
    assert s["collective_ms"] == 0.15
