"""Survival-kit tests (bert_pytorch_tpu/resilience/, docs/RESILIENCE.md):
integrity sidecars + quarantine/fallback, layered preemption handling with
the emergency checkpoint, the hung-step watchdog, the supervisor's
decision table, serving graceful drain — and the headline chaos drill:
a SIGKILLed + supervised pretraining run bit-identical to an
uninterrupted one, on both data planes, packing on."""

import http.client
import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.resilience import (  # noqa: E402
    EXIT_CRASH_LOOP, EXIT_NONFINITE_HALT, EXIT_WATCHDOG_DEVICE_HANG,
    EXIT_WATCHDOG_INPUT_STARVED, CorruptCheckpointError, HungStepWatchdog,
    PreemptionGuard, latest_step_on_disk, quarantine_step, verify_step_dir,
    write_step_manifest)
from bert_pytorch_tpu.telemetry.registry import MetricsRegistry  # noqa: E402


# -- integrity sidecars (jax-free) -------------------------------------------


def _fake_step_dir(tmp_path, step=4):
    sd = tmp_path / str(step)
    (sd / "state").mkdir(parents=True)
    (sd / "extra").mkdir()
    (sd / "state" / "d0").write_bytes(b"\x01" * 4096)
    (sd / "state" / "d1").write_bytes(b"\x02" * 512)
    (sd / "extra" / "metadata").write_text('{"sampler": {"index": 8}}')
    (sd / "_CHECKPOINT_METADATA").write_text("{}")
    return sd


def test_manifest_verify_clean_and_corrupt(tmp_path):
    sd = _fake_step_dir(tmp_path)
    assert verify_step_dir(str(sd)) is None  # no sidecar yet
    write_step_manifest(str(sd), 4, extra_echo={"sampler": {"index": 8}},
                        provenance={"git_sha": "abc"})
    assert verify_step_dir(str(sd)) == []
    # bit-flip a data file: the error names the failed ITEM
    raw = bytearray((sd / "state" / "d0").read_bytes())
    raw[2048] ^= 0xFF
    (sd / "state" / "d0").write_bytes(bytes(raw))
    errors = verify_step_dir(str(sd))
    assert errors and "item 'state' digest mismatch" in errors[0]
    # a MISSING file and an EXTRA file are also corruption
    (sd / "extra" / "metadata").unlink()
    errors = verify_step_dir(str(sd))
    assert any("'extra'" in e and "missing" in e for e in errors)
    # torn sidecar: itself evidence of a torn shutdown
    (sd / "integrity.json").write_text('{"items": {"state"')
    with pytest.raises(CorruptCheckpointError, match="unreadable"):
        verify_step_dir(str(sd))


def test_quarantine_and_disk_scan(tmp_path):
    for step in (2, 4, 6):
        _fake_step_dir(tmp_path, step)
    (tmp_path / "6.orbax-checkpoint-tmp-123").mkdir()  # in-flight: ignored
    assert latest_step_on_disk(str(tmp_path)) == 6
    dst = quarantine_step(str(tmp_path), 6)
    assert dst.endswith("6.corrupt") and os.path.isdir(dst)
    assert latest_step_on_disk(str(tmp_path)) == 4
    # a second quarantine of a re-created step 6 gets a fresh suffix
    _fake_step_dir(tmp_path, 6)
    assert quarantine_step(str(tmp_path), 6).endswith("6.corrupt2")


# -- checkpoint manager: sidecar write + corrupt fallback (satellite bugfix) -


def test_checkpoint_fallback_quarantines_and_restores_next(tmp_path):
    """Corrupt newest -> quarantine (warning names the failed item) ->
    fallback restores next-newest; and the restore_either_layout bugfix:
    a digest mismatch short-circuits as CorruptCheckpointError instead of
    being masked by the layout retry."""
    from bert_pytorch_tpu.resilience.chaos import corrupt_newest_checkpoint
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager

    reg = MetricsRegistry()
    warnings_seen = []
    mgr = CheckpointManager(str(tmp_path / "ck"), registry=reg,
                            log=warnings_seen.append)
    template = {"w": np.arange(64, dtype=np.float32),
                "b": {"x": np.ones((4, 4), np.float32)}}
    for step in (2, 4, 6):
        state = {"w": template["w"] + step,
                 "b": {"x": template["b"]["x"] * step}}
        assert mgr.save(step, state, extra={"sampler": {"index": step}})
    mgr.wait()
    assert reg.counter("bert_ckpt_saves_total").value() == 3
    for step in (2, 4, 6):
        assert mgr.verify(step) == []

    corrupt_newest_checkpoint(mgr.directory, log=lambda m: None)

    # the bugfix: restore_either_layout surfaces the corruption directly
    with pytest.raises(CorruptCheckpointError, match="digest mismatch"):
        mgr.restore_either_layout(template, step=6)

    state, extra, step = mgr.restore_with_fallback(template)
    assert step == 4 and extra["sampler"]["index"] == 4
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  template["w"] + 4)
    assert any("step 6 is CORRUPT" in w and "Quarantined" in w
               and "'state'" in w for w in warnings_seen)
    assert os.path.isdir(os.path.join(mgr.directory, "6.corrupt"))
    assert mgr.all_steps(read=True) == [2, 4]

    # a TORN sidecar (not just mismatched digests) must also quarantine
    # and walk — not crash the resume (regression: verify ran outside
    # the fallback walk's try block)
    with open(os.path.join(mgr.directory, "4", "integrity.json"),
              "w") as f:
        f.write('{"items": {"state"')
    state, extra, step = mgr.restore_with_fallback(template)
    assert step == 2
    assert os.path.isdir(os.path.join(mgr.directory, "4.corrupt"))
    assert any("unreadable" in w for w in warnings_seen)
    mgr.close()


def test_fallback_defers_quarantine_for_unverifiable_checkpoints(tmp_path):
    """Sidecar-less (legacy) checkpoints that fail to restore are NOT
    quarantined unless a deeper checkpoint proves the environment can
    restore at all — an environmental failure (config drift, transient
    FS error) that hits every step must surface the error and rename
    NOTHING, never silently discard all prior training."""
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager

    warns = []
    mgr = CheckpointManager(str(tmp_path / "ck"), log=warns.append)
    template = {"w": np.arange(8, dtype=np.float32)}
    for step in (2, 4):
        assert mgr.save(step, {"w": template["w"] + step})
    mgr.wait()
    for step in (2, 4):  # strip sidecars -> pre-round-17 checkpoints
        os.remove(os.path.join(mgr.directory, str(step),
                               "integrity.json"))

    # environmental failure: a wrong template fails EVERY step — the
    # original error surfaces, no .corrupt renames happen
    with pytest.raises(Exception) as e:
        mgr.restore_with_fallback({"different": {"tree": np.zeros(3)}})
    assert not isinstance(e.value, CorruptCheckpointError)
    assert not any(n.endswith(".corrupt")
                   for n in os.listdir(mgr.directory))

    # genuinely torn newest (core orbax file gone): older restores, which
    # proves the environment works — THEN the torn one is quarantined
    os.remove(os.path.join(mgr.directory, "4", "state",
                           "manifest.ocdbt"))
    state, extra, step = mgr.restore_with_fallback(template)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  template["w"] + 2)
    assert os.path.isdir(os.path.join(mgr.directory, "4.corrupt"))
    assert any("quarantine deferred" in w for w in warns)
    mgr.close()


# -- watchdog ----------------------------------------------------------------


def test_watchdog_classifies_and_aborts(tmp_path):
    """Fed real StepWatch phase transitions: a stalled dispatch is a
    device hang (exit 72), a stalled data_wait is input starvation
    (exit 73); stacks land on disk; warn mode trips once per stall."""
    from bert_pytorch_tpu.telemetry.stepwatch import StepWatch

    reg = MetricsRegistry()
    exits = []
    logs = []
    wd = HungStepWatchdog(timeout_s=0.15, action="abort", registry=reg,
                          log=logs.append, out_dir=str(tmp_path),
                          exit_fn=exits.append)
    sw = StepWatch(flops_per_step=1, seqs_per_step=1, seq_len=8,
                   peak_flops=None)
    sw.phase_listener = wd.on_phase
    wd.start()
    try:
        with sw.phase("dispatch"):
            time.sleep(0.5)
        deadline = time.time() + 2
        while not exits and time.time() < deadline:
            time.sleep(0.01)
        assert exits == [EXIT_WATCHDOG_DEVICE_HANG]
        assert wd.last_stall["kind"] == "device_hang"
        with sw.phase("data_wait"):
            time.sleep(0.5)
        deadline = time.time() + 2
        while len(exits) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert exits[1] == EXIT_WATCHDOG_INPUT_STARVED
    finally:
        wd.close()
    stacks = [f for f in os.listdir(tmp_path)
              if f.startswith("watchdog_stacks_")]
    assert any(f.endswith("device_hang.txt") for f in stacks), stacks
    text = (tmp_path / [f for f in stacks
                        if f.endswith("device_hang.txt")][0]).read_text()
    # all-thread dump names the wedged main-thread frame
    assert "thread" in text and "time.sleep" in text
    assert "phase=dispatch" in text
    prom = reg.render_prometheus()
    assert 'bert_watchdog_stalls_total{kind="device_hang"} 1' in prom
    assert 'bert_watchdog_stalls_total{kind="input_starvation"} 1' in prom
    assert any("WATCHDOG" in m and "device_hang" in m for m in logs)
    # a fast phase never trips
    assert wd.stalls == 2


# -- supervisor (jax-free) ---------------------------------------------------


def _fake_child(tmp_path, script):
    path = tmp_path / "child.py"
    path.write_text(script)
    return [sys.executable, str(path)]


def test_supervisor_restarts_until_success(tmp_path):
    """Death -> restart with lineage env; checkpoint progress resets the
    crash-loop counter; clean exit ends supervision with 0."""
    from tools.supervise import supervise

    ck = tmp_path / "ck"
    ck.mkdir()
    cmd = _fake_child(tmp_path, f"""
import os, sys
attempt = int(os.environ["BERT_SUPERVISOR_RESTARTS"])
assert os.environ["BERT_SUPERVISED"] == "1"
os.makedirs(os.path.join({str(ck)!r}, str(2 + 2 * attempt)))
sys.exit(0 if attempt == 2 else 9)
""")
    rc = supervise(cmd, str(ck), max_restarts=5, backoff_base=0.01,
                   backoff_max=0.02, log=lambda m: None)
    assert rc == 0
    assert latest_step_on_disk(str(ck)) == 6  # three attempts progressed


def test_supervisor_halt_code_awareness(tmp_path):
    """NonFiniteHalt (71) and watchdog device hang (72) are NOT retried;
    input starvation (73) is."""
    from tools.supervise import supervise

    for code, want_attempts in ((EXIT_NONFINITE_HALT, 1),
                                (EXIT_WATCHDOG_DEVICE_HANG, 1)):
        counter = tmp_path / f"n{code}"
        cmd = _fake_child(tmp_path, f"""
import os, sys
p = {str(counter)!r}
n = int(open(p).read()) if os.path.exists(p) else 0
open(p, "w").write(str(n + 1))
sys.exit({code})
""")
        rc = supervise(cmd, str(tmp_path / "ck0"), max_restarts=5,
                       backoff_base=0.01, log=lambda m: None)
        assert rc == code
        assert int(counter.read_text()) == want_attempts
    # 73 (input starvation) IS retried — and without checkpoint progress
    # the crash-loop detector ends it with 74
    counter = tmp_path / "n73"
    cmd = _fake_child(tmp_path, f"""
import os, sys
p = {str(counter)!r}
n = int(open(p).read()) if os.path.exists(p) else 0
open(p, "w").write(str(n + 1))
sys.exit({EXIT_WATCHDOG_INPUT_STARVED})
""")
    rc = supervise(cmd, str(tmp_path / "ck1"), max_restarts=10,
                   crash_loop_tolerance=3, backoff_base=0.01,
                   backoff_max=0.02, log=lambda m: None)
    assert rc == EXIT_CRASH_LOOP
    assert int(counter.read_text()) == 3


# -- chaos monkey (jax-free) -------------------------------------------------


def test_chaos_disarms_on_supervised_restart(monkeypatch):
    """Chaos fires only in the first incarnation: the restarted run must
    sail PAST the injection step, or every drill is a crash loop."""
    from bert_pytorch_tpu.resilience.chaos import ChaosMonkey

    monkeypatch.setenv("BERT_SUPERVISOR_RESTARTS", "1")
    logs = []
    monkey = ChaosMonkey("sigkill_at_step", 3, log=logs.append)
    assert monkey.mode is None
    monkey.before_dispatch(3)  # must be inert
    assert any("disarmed" in m for m in logs)

    monkeypatch.setenv("BERT_SUPERVISOR_RESTARTS", "0")
    armed = ChaosMonkey("stall_dispatch", 3, stall_secs=0.01,
                        log=logs.append)
    assert armed.mode == "stall_dispatch"
    armed.stall(2)   # wrong step: no-op
    assert not armed._fired
    armed.stall(3)   # fires once
    assert armed._fired
    armed.stall(3)   # one-shot
    with pytest.raises(ValueError, match="chaos mode"):
        ChaosMonkey("explode", 1)


# -- preemption guard layering (jax-free) ------------------------------------


def test_preemption_guard_layers_and_restores(tmp_path):
    """Guard chains to the handler installed before it (the flight
    recorder's), counts the preemption, and close() restores the chain
    exactly — the satellite signal-layering contract."""
    from bert_pytorch_tpu.telemetry.flight_recorder import FlightRecorder

    before = signal.getsignal(signal.SIGTERM)
    rec = FlightRecorder(str(tmp_path))
    rec.install_crash_handlers()
    reg = MetricsRegistry()
    guard = PreemptionGuard(registry=reg, log=lambda m: None)
    guard.install()
    handler = signal.getsignal(signal.SIGTERM)
    assert handler == guard._on_signal  # guard on top
    with pytest.raises(SystemExit) as e:
        handler(signal.SIGTERM, None)  # chain: guard -> recorder -> exit
    assert e.value.code == 128 + signal.SIGTERM
    assert guard.preempted_signal == signal.SIGTERM
    assert reg.counter("bert_preemptions_total").value() == 1
    # close in the entry point's order: guard first, recorder second
    guard.close()
    assert signal.getsignal(signal.SIGTERM) == rec._on_signal
    rec.close()
    assert signal.getsignal(signal.SIGTERM) == before


# -- serving graceful drain --------------------------------------------------


def test_frontend_drain_finishes_inflight_and_sheds_new():
    from bert_pytorch_tpu.serving.frontend import ServingFrontend

    release = threading.Event()
    entered = threading.Event()

    def slow_service(body):
        entered.set()
        release.wait(timeout=5)
        return {"ok": True}

    reg = MetricsRegistry(constant_labels={"phase": "serve"})
    fe = ServingFrontend({"squad": slow_service}, reg,
                         healthz_fn=lambda: {}, port=0, host="127.0.0.1")
    try:
        results = {}

        def fire():
            c = http.client.HTTPConnection("127.0.0.1", fe.port, timeout=10)
            c.request("POST", "/v1/squad", body=json.dumps({"q": 1}),
                      headers={"Content-Type": "application/json"})
            r = c.getresponse()
            results["inflight"] = (r.status, r.read())
            c.close()

        t = threading.Thread(target=fire)
        t.start()
        assert entered.wait(timeout=5)
        fe.begin_drain()
        # new admission sheds 503 + Retry-After while one is in flight
        c = http.client.HTTPConnection("127.0.0.1", fe.port, timeout=10)
        c.request("POST", "/v1/squad", body="{}")
        r = c.getresponse()
        assert r.status == 503
        assert r.getheader("Retry-After") is not None
        body = r.read()
        assert b"draining" in body
        # /healthz keeps answering and reports the drain
        c.request("GET", "/healthz")
        h = json.loads(c.getresponse().read())
        assert h["draining"] is True and h["inflight"] == 1
        c.close()
        assert fe.wait_idle(timeout=0.05) is False  # still in flight
        release.set()
        assert fe.wait_idle(timeout=5) is True
        t.join(timeout=5)
        assert results["inflight"][0] == 200  # admitted request finished
    finally:
        release.set()
        fe.close()


# -- /healthz checkpoint freshness + supervisor lineage ----------------------


def test_healthz_checkpoint_freshness_and_restart_gauge(tmp_path,
                                                        monkeypatch):
    from bert_pytorch_tpu.telemetry import init_run
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager

    monkeypatch.setenv("BERT_SUPERVISOR_RESTARTS", "2")
    tel = init_run(phase="pretrain", log_prefix=None, verbose=False,
                   metrics_port=0, metrics_host="127.0.0.1")
    try:
        mgr = CheckpointManager(str(tmp_path / "ck"),
                                registry=tel.registry)
        mgr.save(7, {"w": np.ones(4, np.float32)})
        mgr.wait()
        tel.attach_checkpoints(mgr)
        conn = http.client.HTTPConnection("127.0.0.1", tel.server.port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        h = json.loads(conn.getresponse().read())
        assert h["last_checkpoint_step"] == 7
        assert 0 <= h["seconds_since_checkpoint"] < 120
        assert h["supervisor_restarts"] == 2
        conn.request("GET", "/metrics")
        prom = conn.getresponse().read().decode()
        assert "bert_supervisor_restarts" in prom
        assert "bert_ckpt_saves_total" in prom
        assert "bert_preemptions_total" not in prom  # guard not built here
        conn.close()
        mgr.close()
    finally:
        tel.close()


# -- entry-point e2e (shared fixture with the headline drill) ----------------
#
# One drill-config workdir + ONE uninterrupted reference run serve both
# the SIGTERM zero-loss e2e and the offline headline drill. Every
# session — reference included — is a subprocess under the drill's
# shared env (8-device CPU platform, reduced XLA opt level), so the
# bit-identity comparisons never cross program families and tier-1
# stays inside its wall-clock budget on a one-core box.


@pytest.fixture(scope="module")
def offline_ref(tmp_path_factory):
    from tools.resilience_drill import run_reference

    work = str(tmp_path_factory.mktemp("drill_offline"))
    ref = run_reference("offline", work)
    return work, ref


def test_sigterm_chaos_lands_bundle_and_emergency_ckpt_zero_loss(
        offline_ref):
    """One SIGTERM lands BOTH the flight-recorder crash bundle AND the
    emergency checkpoint of the last completed step (mid-interval, not a
    boundary; label-coherent sampler cursor), exiting 143; the atexit
    backstop never double-dumps (handler layering/restoration is pinned
    by the jax-free unit above). The run then RESUMES FROM the emergency
    checkpoint to completion, and its combined metric stream equals the
    uninterrupted control run's bit for bit — zero completed steps lost,
    zero batches skipped or replayed."""
    from tools.resilience_drill import (KILL_AT, MAX_STEPS, drill_argv,
                                        metric_stream, run_session)

    work, ref = offline_ref
    out = os.path.join(work, "out_sigterm")
    # fire so the last COMPLETED step falls mid-interval (an on-boundary
    # signal has nothing to save — the periodic checkpoint already has it)
    term_at = KILL_AT - 1
    done = term_at - 1
    assert done % 2 == 1, "chaos step must leave a mid-interval last step"
    rc = run_session(drill_argv(
        "offline", work, out,
        extra=["--chaos", "sigterm_at_step",
               "--chaos_step", str(term_at)]))
    assert rc == 128 + signal.SIGTERM  # SystemExit(143) contract

    log = open(os.path.join(out, "drill.txt")).read()
    assert f"CHAOS: raising SIGTERM before step {term_at}" in log
    assert f"emergency checkpoint saved at step {done}" in log
    ckpts = os.path.join(out, "pretrain_ckpts")
    assert latest_step_on_disk(ckpts) == done
    # the sidecar landed synchronously with the emergency save, cursor
    # echo included
    sidecar = os.path.join(ckpts, str(done), "integrity.json")
    assert os.path.isfile(sidecar)
    echo = json.load(open(sidecar))
    assert echo["extra_echo"]["sampler"]["index"] >= 0
    # ONE crash bundle (atexit backstop did not double-dump)
    bundles = os.listdir(os.path.join(out, "repro_bundles"))
    assert len(bundles) == 1 and "systemexit" in bundles[0]

    # resume FROM the emergency checkpoint to completion: the combined
    # stream must equal the uninterrupted reference bit for bit (zero-
    # loss is not enough — the emergency cursor must not skip/replay a
    # batch)
    assert run_session(drill_argv("offline", work, out)) == 0
    log = open(os.path.join(out, "drill.txt")).read()
    assert f"auto-resumed from step {done}" in log
    stream = metric_stream(out)
    assert set(stream) == set(range(1, MAX_STEPS + 1))
    assert stream == metric_stream(ref)


# -- the headline drill ------------------------------------------------------


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_headline_sigkill_supervised_bit_identical_both_planes(
        offline_ref, tmp_path_factory):
    """Acceptance: a pretraining run SIGKILLed mid-interval, restarted by
    tools/supervise.py, produces final params and per-step metric stream
    bit-identical to an uninterrupted run — offline AND streaming planes,
    --packing on (tools/resilience_drill.py is the single source of
    truth; scripts/check_resilience.sh runs the same drill plus the
    corrupt-newest variant as a standalone CI gate)."""
    from tools.resilience_drill import drill_sigkill

    work, ref = offline_ref
    # offline: reuse the module's uninterrupted reference; the chaos +
    # restart sessions are real subprocesses under tools/supervise.py
    errors = drill_sigkill("offline", work, ref_out=ref)
    assert not errors, "[offline] " + "; ".join(errors)

    stream_work = str(tmp_path_factory.mktemp("drill_stream"))
    errors = drill_sigkill("stream", stream_work)
    assert not errors, "[stream] " + "; ".join(errors)
