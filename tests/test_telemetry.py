"""Tier-1 telemetry tests on the 8-device CPU mesh (conftest.py): health
pack flags injected non-finite steps, --nonfinite_action=skip preserves
state bit-exact, grad-spike z-score fires, StepWatch MFU matches a
hand-computed value, CompileWatch counts a forced recompile, and a full
run_pretraining.main() run logs perf/health records through every sink."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.optim import schedulers
from bert_pytorch_tpu.optim.lamb import lamb, default_weight_decay_mask
from bert_pytorch_tpu.telemetry import (CompileWatch, HealthConfig,
                                        StepWatch, collect_provenance,
                                        flops_per_seq, hbm_snapshot,
                                        init_telemetry_state)
from bert_pytorch_tpu.telemetry.health import health_update
from bert_pytorch_tpu.telemetry.stepwatch import lookup_peak_flops
from bert_pytorch_tpu.training import build_pretrain_step, make_sharded_state
from bert_pytorch_tpu.training.pretrain import (_pretrain_loss_fn,
                                                chain_steps,
                                                stack_microbatches)

TINY = BertConfig(
    vocab_size=128, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, next_sentence=True,
    dtype="float32", fused_ops=False, attention_impl="xla",
    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
)


def _batch(global_batch=8, seq=16, vocab=128, seed=0, accum=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(5, vocab, (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -1, np.int32)
    labels[:, 2] = ids[:, 2]
    batch = {
        "input_ids": ids,
        "token_type_ids": np.zeros((global_batch, seq), np.int32),
        "attention_mask": np.ones((global_batch, seq), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (global_batch,)
                                            ).astype(np.int32),
    }
    return {k: jnp.asarray(v)
            for k, v in stack_microbatches(batch, accum).items()}


def _poison_loss_builder(model):
    """Standard pretraining loss, except a batch whose next_sentence_labels
    are all 9 (a value the loader never produces) multiplies the loss by
    inf — the in-graph analog of a data-corruption NaN batch, giving both a
    non-finite loss AND non-finite gradients."""
    base = _pretrain_loss_fn(model, None)

    def loss_fn(params, batch, rng, deterministic=False):
        loss, aux = base(params, batch, rng, deterministic)
        poison = jnp.all(batch["next_sentence_labels"] == 9)
        return loss * jnp.where(poison, jnp.inf, 1.0), aux

    return loss_fn


def _make_step(action: str):
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100,
                                            warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask)
    step_fn = build_pretrain_step(
        model, tx, schedule=sched, accum_steps=1,
        loss_fn_builder=_poison_loss_builder,
        health=HealthConfig(action=action))
    batch = _batch()
    init_fn = lambda r: model.init(r, batch["input_ids"][0],
                                   batch["token_type_ids"][0],
                                   batch["attention_mask"][0])
    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    state = state.replace(telemetry=init_telemetry_state())
    return jax.jit(step_fn, donate_argnums=(0,)), state, batch


def _poisoned(batch):
    out = dict(batch)
    out["next_sentence_labels"] = jnp.full_like(
        batch["next_sentence_labels"], 9)
    return out


# -- health pack ------------------------------------------------------------

def test_health_pack_flags_injected_nonfinite():
    jit_step, state, batch = _make_step("log")
    state, m = jit_step(state, batch, jax.random.PRNGKey(0))
    assert float(m["loss_nonfinite"]) == 0
    assert float(m["grad_nonfinite"]) == 0
    # per-group keys present and zero on a clean step
    assert float(m["grad_nonfinite_bert"]) == 0

    state, m = jit_step(state, _poisoned(batch), jax.random.PRNGKey(1))
    assert float(m["loss_nonfinite"]) == 1
    assert float(m["grad_nonfinite"]) > 0
    assert float(m["grad_nonfinite_bert"]) > 0
    assert not np.isfinite(float(m["loss"]))
    # action=log: the poisoned update went through (params now non-finite)
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    assert not np.isfinite(leaf).all()


def test_nonfinite_action_skip_preserves_state():
    """THE acceptance property: a poisoned batch under action='skip' leaves
    params and optimizer state bit-identical — the guard must be in-graph
    because the host's metric readback is one step behind dispatch."""
    jit_step, state, batch = _make_step("skip")
    for i in range(2):
        state, _ = jit_step(state, batch, jax.random.PRNGKey(i))
    params_before = jax.tree.map(np.asarray, state.params)
    opt_before = jax.tree.map(np.asarray, state.opt_state)
    count_before = int(state.telemetry.count)

    state, m = jit_step(state, _poisoned(batch), jax.random.PRNGKey(9))
    assert float(m["skipped_nonfinite"]) == 1
    for a, b in zip(jax.tree.leaves(params_before),
                    jax.tree.leaves(jax.tree.map(np.asarray, state.params))):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(opt_before),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 state.opt_state))):
        np.testing.assert_array_equal(a, b)
    # bad step did not enter the EMA, step still advanced
    assert int(state.telemetry.count) == count_before
    assert int(state.step) == 3

    # and the run keeps training after the skip
    state, m = jit_step(state, batch, jax.random.PRNGKey(10))
    assert float(m["skipped_nonfinite"]) == 0
    assert np.isfinite(float(m["loss"]))


def test_chain_steps_sticky_health_flags():
    """steps_per_loop>1 returns only the LAST inner step's metrics; a flag
    raised by an earlier inner step must survive via max-accumulation."""
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100,
                                            warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask)
    step_fn = build_pretrain_step(
        model, tx, schedule=sched, accum_steps=1,
        loss_fn_builder=_poison_loss_builder,
        health=HealthConfig(action="skip"))
    batch = _batch()
    init_fn = lambda r: model.init(r, batch["input_ids"][0],
                                   batch["token_type_ids"][0],
                                   batch["attention_mask"][0])
    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    state = state.replace(telemetry=init_telemetry_state())
    chained = jax.jit(chain_steps(step_fn, 2, per_step_batch=True),
                      donate_argnums=(0,))
    # inner step 0 poisoned, inner step 1 clean -> last metrics are from
    # the clean step but the sticky flags must still show the poison
    two = {k: jnp.stack([_poisoned(batch)[k], batch[k]]) for k in batch}
    state, m = chained(state, two, jax.random.PRNGKey(5))
    assert float(m["grad_nonfinite"]) > 0
    # per-group localization survives the loop too (is_sticky_metric)
    assert float(m["grad_nonfinite_bert"]) > 0
    assert float(m["skipped_nonfinite"]) == 1
    assert np.isfinite(float(m["loss"]))  # last (clean) step's loss


def test_grad_spike_zscore_fires_after_warmup():
    cfg = HealthConfig(warmup_steps=5, spike_z=4.0, ema_decay=0.9)
    telem = init_telemetry_state()
    params = {"w": jnp.ones((4,))}
    update = jax.jit(lambda t, gn, bad: health_update(
        cfg, t, gn, bad, params))
    bad = jnp.asarray(False)
    for _ in range(20):  # steady stream of ~1.0 norms
        telem, m = update(telem, jnp.float32(1.0), bad)
    assert int(m["grad_spike"]) == 0
    telem, m = update(telem, jnp.float32(100.0), bad)  # 100x spike
    assert int(m["grad_spike"]) == 1
    assert float(m["grad_norm_z"]) > 4.0
    # EMA keeps tracking (spike folded in, no NaN)
    assert np.isfinite(float(telem.grad_norm_ema))


def test_health_update_param_norm_drift():
    cfg = HealthConfig()
    telem = init_telemetry_state()
    telem, m = health_update(cfg, telem, jnp.float32(1.0),
                             jnp.asarray(False), {"w": jnp.full((4,), 3.0)})
    assert m["param_norm"] == pytest.approx(6.0)  # sqrt(4*9)
    assert m["param_norm_drift"] == 0.0           # no previous norm yet
    telem, m = health_update(cfg, telem, jnp.float32(1.0),
                             jnp.asarray(False), {"w": jnp.full((4,), 3.3)})
    assert float(m["param_norm_drift"]) == pytest.approx(0.1, rel=1e-5)


# -- StepWatch / MFU --------------------------------------------------------

def test_flops_per_seq_matches_hand_computed():
    cfg = BertConfig(vocab_size=100, hidden_size=10, num_hidden_layers=3,
                     num_attention_heads=2, intermediate_size=40,
                     max_position_embeddings=64)
    S, n_pred = 8, 4
    # trunk: L * (4*E^2 + 2*E*F) * S = 3 * (400 + 800) * 8 = 28800
    # head: (V*E + E*E) * n_pred = (1000 + 100) * 4 = 4400
    # dense total: 6 * (28800 + 4400) = 199200
    # attention: 12 * L * E * S^2 = 12 * 3 * 10 * 64 = 23040
    assert flops_per_seq(cfg, S, cfg.vocab_size, n_pred) == 199200 + 23040


def test_stepwatch_mfu_and_phases_hand_computed():
    clock = [0.0]
    sw = StepWatch(flops_per_step=2e9, seqs_per_step=32, seq_len=128,
                   peak_flops=1e12, log_freq=4, time_fn=lambda: clock[0])
    rec = None
    for _ in range(4):
        with sw.phase("data_wait"):
            clock[0] += 0.1
        with sw.phase("dispatch"):
            clock[0] += 0.4
        rec = sw.step_done()
    assert rec is not None
    # 4 steps in 2.0s wall: 0.5 s/step, 64 seq/s, 8192 tok/s
    assert rec["steps"] == 4
    assert rec["step_time_ms"] == pytest.approx(500.0)
    assert rec["seq_per_sec"] == pytest.approx(64.0)
    assert rec["tokens_per_sec"] == pytest.approx(64.0 * 128)
    # MFU = 2e9 * 4 / 2.0 / 1e12 = 0.004
    assert rec["mfu"] == pytest.approx(0.004)
    assert rec["data_wait_ms"] == pytest.approx(100.0)
    assert rec["dispatch_ms"] == pytest.approx(400.0)
    # interval reset: next boundary needs another log_freq steps
    assert sw.step_done() is None


def test_stepwatch_steps_per_loop_counting():
    clock = [0.0]
    sw = StepWatch(flops_per_step=1e9, seqs_per_step=8, seq_len=64,
                   peak_flops=1e12, log_freq=4, time_fn=lambda: clock[0])
    clock[0] = 2.0
    rec = sw.step_done(n=4)  # one dispatch, 4 optimization steps
    assert rec["steps"] == 4
    assert rec["step_time_ms"] == pytest.approx(500.0)
    assert rec["seq_per_sec"] == pytest.approx(16.0)


def test_lookup_peak_flops():
    assert lookup_peak_flops("TPU v5 lite") == 197e12
    assert lookup_peak_flops("TPU v5p chip") == 459e12
    assert lookup_peak_flops("cpu") is None


def test_lookup_peak_flops_dtype_aware():
    # MFU must be quoted against the peak of the compute dtype: the MXU
    # runs f32 matmuls at half the bf16 rate on every listed generation.
    # Pin both dtypes on two generations so a table edit that forgets the
    # ratio (or a caller that quotes bf16 runs against f32 peak) fails.
    assert lookup_peak_flops("TPU v4", dtype="bf16") == 275e12
    assert lookup_peak_flops("TPU v4", dtype="f32") == 137.5e12
    assert lookup_peak_flops("TPU v5p chip", dtype="bfloat16") == 459e12
    assert lookup_peak_flops("TPU v5p chip", dtype="float32") == 229.5e12
    # config.dtype strings pass straight through
    assert lookup_peak_flops("TPU v5 lite", dtype="float32") == 98.5e12
    assert lookup_peak_flops("cpu", dtype="f32") is None
    with pytest.raises(ValueError):
        lookup_peak_flops("TPU v4", dtype="int8")


# -- CompileWatch / HBM -----------------------------------------------------

def test_compile_watch_counts_forced_recompile():
    warnings = []
    cw = CompileWatch(warn=warnings.append).install()
    try:
        @jax.jit
        def f(x):
            return x * 2 + 1

        x2, x3 = jnp.zeros((2,)), jnp.zeros((3,))  # helper compiles happen
        f(x2)                                # compile (warmup)
        f(x2)                                # cache hit: no new compile
        n_warm = cw.compiles
        assert n_warm >= 1
        cw.mark_steady()
        assert warnings == []
        f(x3)                                # new shape -> forced recompile
        assert cw.compiles == n_warm + 1
        assert cw.compiles_after_steady == 1
        assert len(warnings) == 1 and "RECOMPILE" in warnings[0]
        assert cw.compile_secs > 0
        snap = cw.snapshot()
        assert snap["recompiles_after_warmup"] == 1
    finally:
        cw.uninstall()
    # uninstalled: further compiles are not counted
    n = cw.compiles

    @jax.jit
    def g(x):
        return x - 1

    g(jnp.zeros((2,)))
    assert cw.compiles == n


def test_hbm_snapshot_cpu_safe():
    # CPU PJRT exposes no memory_stats — must degrade to {} (not raise);
    # on TPU the same call returns hbm_peak_bytes etc.
    snap = hbm_snapshot()
    assert isinstance(snap, dict)
    for v in snap.values():
        assert v >= 0


# -- provenance -------------------------------------------------------------

def test_provenance_collect_fields():
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    p = collect_provenance(mesh=mesh_lib.make_mesh())
    assert p["jax_version"] == jax.__version__
    assert p["git_sha"]  # "unknown" at worst, never empty
    assert p["platform"] == "cpu"
    assert p["mesh"]["data"] == 8
    assert "libtpu_init_args" in p and "overlap_pack_active" in p


# -- end-to-end: every sink gets perf + health records ----------------------

@pytest.fixture
def workdir(tmp_path):
    from tests.test_data import write_shard

    data = tmp_path / "data"
    data.mkdir()
    for i in range(2):
        write_shard(data / f"shard_{i}.hdf5", 32, seed=i)
    model_cfg = {
        "vocab_size": 128, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "tokenizer": "wordpiece", "fused_ops": False,
        "attention_impl": "xla",
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))
    return tmp_path, data, cfg_path


def test_run_pretraining_logs_perf_and_health_through_sinks(workdir):
    """Acceptance: a CPU-backend pretraining run logs per-interval
    step_time/seq_per_sec/MFU/data_wait and per-step health fields through
    every enabled sink, stamped with a provenance header."""
    tmp_path, data, cfg_path = workdir
    import run_pretraining

    out = tmp_path / "out"
    argv = ["--input_dir", str(data), "--output_dir", str(out),
            "--model_config_file", str(cfg_path),
            "--mask_token_index", "3", "--dtype", "float32",
            "--vocab_pad_multiple", "8", "--learning_rate", "1e-3",
            "--global_batch_size", "32", "--local_batch_size", "2",
            "--max_steps", "4", "--max_predictions_per_seq", "5",
            "--skip_checkpoint", "--log_freq", "2",
            "--nonfinite_action", "skip"]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 4

    records = [json.loads(l)
               for l in open(out / "logfile.jsonl", encoding="utf-8")]
    by_tag = {}
    for r in records:
        by_tag.setdefault(r["tag"], []).append(r)

    # provenance header first
    assert by_tag["header"][0]["git_sha"]
    assert by_tag["header"][0]["jax_version"] == jax.__version__

    # per-step train records carry the health fields
    train = by_tag["train"]
    assert len(train) == 4
    for r in train:
        assert r["loss_nonfinite"] == 0 and r["grad_nonfinite"] == 0
        assert r["skipped_nonfinite"] == 0
        assert np.isfinite(r["step_loss"]) and r["param_norm"] > 0

    # interval perf records: step_time / seq_per_sec / MFU / data_wait /
    # dispatch / compile counts
    perf = by_tag["perf"]
    assert len(perf) == 2  # steps 2 and 4 at log_freq 2
    for r in perf:
        assert r["step_time_ms"] > 0
        assert r["seq_per_sec"] > 0
        assert r["tokens_per_sec"] > 0
        assert "mfu" in r and r["peak_flops"] > 0
        assert "data_wait_ms" in r and "dispatch_ms" in r
        assert r["compiles"] >= 1
    # warmup closed at the first interval; no recompiles in this run
    assert perf[-1]["recompiles_after_warmup"] == 0

    # same fields reached the CSV sink (header-union schema)
    header = open(out / "logfile_metrics.csv",
                  encoding="utf-8").readline().strip().split(",")
    for col in ("step_loss", "grad_nonfinite", "seq_per_sec", "mfu",
                "data_wait_ms"):
        assert col in header
    # and the text sink
    txt = (out / "logfile.txt").read_text()
    assert "[header]" in txt and "[perf]" in txt and "[train]" in txt


# -- trace summarizer (round 11) ---------------------------------------------

def test_trace_classify_buckets():
    from bert_pytorch_tpu.telemetry.trace import classify

    assert classify("all-gather-start.12") == "collective"
    assert classify("all-reduce.3") == "collective"
    assert classify("reduce-scatter") == "collective"
    assert classify("collective-permute-done.1") == "collective"
    assert classify("fusion.123") == "compute"
    assert classify("dot.1") == "compute"
    assert classify("transpose_copy_fusion") == "compute"
    assert classify("host/data_wait") == "host/data_wait"
    # framework wrappers and Python frames are excluded, not "compute"
    assert classify("ThunkExecutor::Execute") is None
    assert classify("PjitFunction(train_step)") is None
    assert classify("$profiler.py:91 trace") is None


def test_trace_summarize_events_interval_merge_and_normalization():
    """Nested same-bucket events are merged (no double count), buckets are
    keyed per (pid, tid), and --steps/--devices produce the per-step
    per-device numbers bench.py embeds in MULTICHIP_r*.json."""
    from bert_pytorch_tpu.telemetry.trace import summarize_events

    us = 1000.0  # 1 ms in trace-event microseconds
    ev = [
        # device thread 1: a 4 ms all-gather with a 2 ms NESTED re-report
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-gather.1",
         "ts": 0.0, "dur": 4 * us},
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-gather.1",
         "ts": 1 * us, "dur": 2 * us},
        # same thread: 6 ms of compute, disjoint
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.9",
         "ts": 4 * us, "dur": 6 * us},
        # second device thread: 2 ms collective
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-reduce-start.2",
         "ts": 0.0, "dur": 2 * us},
        # third device thread: an all-gather CONCURRENT with tid 1's —
        # cross-thread same-op time must SUM (device-time), never merge
        {"ph": "X", "pid": 1, "tid": 3, "name": "all-gather.7",
         "ts": 0.0, "dur": 3 * us},
        # host annotation + excluded wrapper + non-X event
        {"ph": "X", "pid": 2, "tid": 9, "name": "host/h2d",
         "ts": 0.0, "dur": 3 * us},
        {"ph": "X", "pid": 1, "tid": 1, "name": "ThunkExecutor::Run",
         "ts": 0.0, "dur": 50 * us},
        {"ph": "M", "pid": 1, "tid": 1, "name": "all-gather.1"},
    ]
    s = summarize_events(ev, steps=2, n_devices=2)
    assert s["collective_ms"] == 9.0   # 4 (merged) + 2 + 3, not 11
    assert s["compute_ms"] == 6.0
    assert s["host_ms"] == {"h2d": 3.0}
    assert s["collective_fraction"] == 0.6
    # per-op: tid 1's nested pair merges to 4, tid 3's concurrent 3 SUMS
    assert s["collective_by_op_ms"] == {"all-gather": 7.0, "all-reduce": 2.0}
    assert s["collective_ms_per_step_device"] == 2.25  # 9 / (2 steps * 2 dev)
    assert s["compute_ms_per_step_device"] == 1.5
    assert s["events_classified"] == 6
