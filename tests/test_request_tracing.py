"""Request-path tracing (round 18): span lifecycle on every terminal
path, flight-recorder retention bounds, strict-JSON export, tail
attribution, cost accounting, and the tracing on/off guarantees.

Pins the acceptance surface:
- a completed request's trace holds exactly the ordered phase vocabulary
  admit -> queue_wait -> pack -> dispatch -> compute -> demux -> respond;
- every error exit closes its trace with the matching terminal span
  (shed 503, timeout 504, too_long 413) and first-finish wins;
- a stolen wave's dispatch span records the hop (queued_on != replica);
- the TraceRing stays bounded at 2*keep_slowest + keep_sampled under a
  burst and never drops the slowest trace;
- /v1/traces-shaped exports are strict JSON in the Chrome trace event
  format, and summarize_request_events names the dominant p99 phase;
- tracing off: bit-identical responses, no ring, no trace objects;
- per-wave device-seconds flow into bert_serve_device_seconds_total and
  the cost-per-1k-tokens gauge; StepWatch perf records carry the
  matching device_seconds_per_step / cost_per_1k_tokens fields;
- the replica queue-depth gauge is fresh on enqueue (not only on pop).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from bert_pytorch_tpu.serving.batcher import (  # noqa: E402
    Overloaded, RequestTimeout, Scheduler, TooLong)
from bert_pytorch_tpu.serving.request_trace import (  # noqa: E402
    REQUEST_PHASES, TERMINAL_SPANS, TraceRing, collect_trace_ids,
    note_trace_id)
from bert_pytorch_tpu.telemetry.stepwatch import (  # noqa: E402
    StepWatch, resolve_cost_per_device_hour)
from bert_pytorch_tpu.telemetry.trace import (  # noqa: E402
    classify, summarize_request_events)


class _EchoEngine:
    """Deterministic jax-free engine stub: forward echoes input_ids so
    demuxed outputs depend on the request content (bit-identity fuel).
    An optional gate jams forward (steal/queue-depth fuel); an optional
    stall delays it (admission-timeout fuel)."""

    buckets = (16,)
    batch_rows = 4
    max_segments = 4
    max_bucket = 16
    n_devices = 2

    def __init__(self, gate=None, stall_s=0.0, name="r0", batch_rows=4):
        self.gate = gate
        self.stall_s = stall_s
        self.name = name
        self.batch_rows = batch_rows

    def select_bucket(self, length):
        return 16 if length <= 16 else None

    def forward(self, task, batch):
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        if self.stall_s:
            time.sleep(self.stall_s)
        ids = np.asarray(batch["input_ids"])
        return ids * 2, ids + 1


def _spans(tr):
    return [s[0] for s in tr.spans]


def _assert_same(a, b, ctx):
    a = a if isinstance(a, tuple) else (a,)
    b = b if isinstance(b, tuple) else (b,)
    assert len(a) == len(b), ctx
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), ctx


# -- span lifecycle on every terminal path ------------------------------------


def test_completed_request_full_span_lifecycle():
    sch = Scheduler(_EchoEngine(), packing=True, batch_wait_ms=0.0).start()
    try:
        h = sch.submit("ner", np.arange(8, dtype=np.int32))
        sch.result(h, timeout=30)
    finally:
        sch.close()
    ok = [t for t in sch.trace_ring.traces() if t.outcome == "ok"]
    assert len(ok) == 1
    tr = ok[0]
    assert _spans(tr) == list(REQUEST_PHASES)
    assert tr.finished and tr.total_ms >= 0
    by_name = {s[0]: s for s in tr.spans}
    _, _, _, compute = by_name["compute"]
    assert compute["replica"] == 0 and compute["bucket"] == 16
    assert compute["n_devices"] == 2
    assert compute["device_seconds"] >= 0
    _, _, _, dispatch = by_name["dispatch"]
    assert dispatch["stolen"] is False
    assert dispatch["queued_on"] == dispatch["replica"] == 0
    # spans are chronologic and non-negative
    for name, t0, t1, _ in tr.spans:
        assert t1 >= t0, name


def test_shed_terminal_span():
    sch = Scheduler(_EchoEngine(), queue_size=2, packing=True)  # no consumer
    ids = np.arange(8, dtype=np.int32)
    for _ in range(2):
        sch.submit("ner", ids)
    with pytest.raises(Overloaded):
        sch.submit("ner", ids)
    shed = [t for t in sch.trace_ring.traces() if t.outcome == "shed"]
    assert len(shed) == 1
    assert _spans(shed[0]) == ["shed"]
    assert shed[0].finished


def test_too_long_terminal_span():
    sch = Scheduler(_EchoEngine(), packing=True)  # submit-side reject only
    with pytest.raises(TooLong):
        sch.submit("ner", np.arange(40, dtype=np.int32))
    tr = sch.trace_ring.traces()
    assert len(tr) == 1 and tr[0].outcome == "too_long"
    assert _spans(tr[0]) == ["too_long"]
    assert tr[0].spans[0][3]["length"] == 40


def test_admission_timeout_terminal_span():
    sch = Scheduler(_EchoEngine(stall_s=0.25, batch_rows=2),
                    admission_timeout_s=0.1,
                    batch_wait_ms=0.0, packing=True).start()
    try:
        ids = np.arange(10, dtype=np.int32)
        handles = [sch.submit("ner", ids) for _ in range(12)]
        outcomes = []
        for h in handles:
            try:
                sch.result(h, timeout=10)
                outcomes.append("ok")
            except RequestTimeout:
                outcomes.append("timeout")
    finally:
        sch.close()
    assert "timeout" in outcomes
    tos = [t for t in sch.trace_ring.traces() if t.outcome == "timeout"]
    assert tos
    for t in tos:
        assert _spans(t)[-1] == "timeout"
        assert t.spans[-1][3]["waited_s"] >= 0.1


def test_stolen_wave_dispatch_span_records_hop():
    """Jam replica 0 on a wave; replica 1 steals the backlog — the served
    requests' dispatch spans must carry stolen=True with the hop."""
    gate0, gate1 = threading.Event(), threading.Event()
    jammed = _EchoEngine(gate=gate0, name="r0")
    free = _EchoEngine(gate=gate1, name="r1")
    sch = Scheduler([jammed, free], packing=True, batch_wait_ms=0.0).start()
    try:
        ids = np.arange(8, dtype=np.int32)
        first = None
        deadline = time.time() + 30
        while first is None and time.time() < deadline:
            while ((sch._inflight[0] or sch._inflight[1])
                   and time.time() < deadline):
                time.sleep(0.005)
            h = sch.submit("ner", ids)
            while (not sch._inflight[0] and not sch._inflight[1]
                   and time.time() < deadline):
                time.sleep(0.005)
            if sch._inflight[0]:
                first = h                  # r0 jams on this wave
            else:                          # r1 grabbed the probe: flush it
                gate1.set()
                sch.result(h, timeout=30)
                gate1.clear()
        assert first is not None, "replica 0 never held a jammed wave"
        gate1.set()
        later = [sch.submit("ner", ids) for _ in range(3)]
        for h in later:
            sch.result(h, timeout=30)      # resolves while r0 still jammed
        gate0.set()
        sch.result(first, timeout=30)
    finally:
        gate0.set()
        gate1.set()
        sch.close()
    stolen = [(t, attrs) for t in sch.trace_ring.traces()
              for name, _, _, attrs in t.spans
              if name == "dispatch" and attrs and attrs.get("stolen")]
    assert stolen, "no dispatch span recorded a steal hop"
    t, attrs = stolen[0]
    assert t.outcome == "ok"
    assert attrs["replica"] != attrs["queued_on"]


# -- flight-recorder retention -------------------------------------------------


def test_trace_ring_bounded_and_keeps_slowest():
    ring = TraceRing(keep_slowest=8, sample_every=10, keep_sampled=5,
                     window_s=3600.0)
    for i in range(500):
        tr = ring.new_trace("ner")
        tr.span("admit", tr.t_admit, tr.t_admit + 1e-4)
        tr.finish("ok", tr.t_admit + i / 1000.0)   # total_ms == i
        ring.add(tr)
    st = ring.stats()
    assert st["seen"] == 500
    assert st["by_outcome"] == {"ok": 500}
    retained = ring.traces()
    assert len(retained) <= 2 * 8 + 5
    # slowest-first ordering and the actual slowest retained
    totals = [t.total_ms for t in retained]
    assert totals == sorted(totals, reverse=True)
    assert totals[0] == pytest.approx(499.0)


def test_trace_ring_window_rotation_keeps_previous_window():
    clock = [0.0]
    ring = TraceRing(keep_slowest=4, sample_every=10**6, window_s=10.0,
                     time_fn=lambda: clock[0])

    def add(total_ms):
        tr = ring.new_trace("ner")
        tr.finish("ok", tr.t_admit + total_ms / 1e3)
        ring.add(tr)
        return tr.trace_id

    t1 = add(100.0)
    clock[0] = 11.0                       # rotate: t1 -> previous window
    t2 = add(50.0)
    ids = {t.trace_id for t in ring.traces()}
    assert {t1, t2} <= ids                # scrape after rotation sees both
    clock[0] = 22.0                       # rotate again: t1 falls off
    t3 = add(25.0)
    ids = {t.trace_id for t in ring.traces()}
    assert t1 not in ids and {t2, t3} <= ids


def test_snapshot_events_strict_json_chrome_schema():
    sch = Scheduler(_EchoEngine(), packing=True, batch_wait_ms=0.0,
                    trace_ring=TraceRing(sample_every=1)).start()
    try:
        handles = [sch.submit("ner", np.arange(4 + i, dtype=np.int32))
                   for i in range(4)]
        for h in handles:
            sch.result(h, timeout=30)
        with pytest.raises(TooLong):
            sch.submit("ner", np.arange(40, dtype=np.int32))
    finally:
        sch.close()
    doc = sch.trace_ring.snapshot_events()
    text = json.dumps(doc, sort_keys=True, allow_nan=False)  # strict JSON
    doc2 = json.loads(text)
    events = doc2["traceEvents"]
    assert events and doc2["displayTimeUnit"] == "ms"
    for ev in events:
        assert ev["ph"] == "X"
        assert ev["name"].startswith("req/")
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        args = ev["args"]
        assert args["trace_id"] and args["task"] == "ner"
        assert args["outcome"] in ("ok",) + TERMINAL_SPANS
        assert isinstance(args["total_ms"], (int, float))
    assert doc2["metadata"]["exported"] == 5
    by = doc2["metadata"]["by_outcome"]
    assert by["ok"] == 4 and by["too_long"] == 1


def test_request_spans_excluded_from_device_classification():
    for phase in REQUEST_PHASES + TERMINAL_SPANS:
        assert classify(f"req/{phase}") is None


# -- tracing on/off: bit identity + overhead ----------------------------------


def test_tracing_off_bit_identical_and_ringless():
    def run(tracing):
        sch = Scheduler(_EchoEngine(), packing=True, batch_wait_ms=0.0,
                        tracing=tracing).start()
        try:
            handles = [sch.submit("ner",
                                  np.arange(3 + i % 8, dtype=np.int32) + 1)
                       for i in range(12)]
            return sch, [sch.result(h, timeout=30) for h in handles]
        finally:
            sch.close()

    sch_on, on = run(True)
    sch_off, off = run(False)
    assert sch_on.trace_ring is not None
    assert sch_off.trace_ring is None
    for i, (a, b) in enumerate(zip(on, off)):
        _assert_same(a, b, f"request {i}: tracing flipped a bit")


def test_span_recording_cost_is_small():
    """Full per-request tracing work (7 spans + finish + ring add) in a
    tight loop. Generous CI bound — the real budget (< 1% of serve p50)
    is measured against the live server and documented in
    docs/OBSERVABILITY.md."""
    ring = TraceRing()
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        tr = ring.new_trace("ner")
        for name in REQUEST_PHASES:
            tr.span(name, t0, t0, replica=0)
        tr.finish("ok", tr.t_admit + 1e-3)
        ring.add(tr)
    per_req = (time.perf_counter() - t0) / n
    assert per_req < 2e-3, f"{per_req * 1e6:.0f}us per traced request"


# -- tail attribution + CLI ----------------------------------------------------


def _synthetic_ring():
    """9 fast compute-dominated traces + 1 slow queue-dominated one on
    replica 1 — the p99 cohort must name queue_wait on r1."""
    ring = TraceRing(sample_every=1)
    for i in range(9):
        tr = ring.new_trace("ner")
        b = tr.t_admit
        tr.span("admit", b, b + 1e-4)
        tr.span("queue_wait", b + 1e-4, b + 1e-3)
        tr.span("compute", b + 1e-3, b + 9e-3, replica=0)
        tr.span("respond", b + 9e-3, b + 1e-2)
        tr.finish("ok", b + 1e-2)
        ring.add(tr)
    tr = ring.new_trace("ner")
    b = tr.t_admit
    tr.span("admit", b, b + 1e-4)
    tr.span("queue_wait", b + 1e-4, b + 0.18)
    tr.span("compute", b + 0.18, b + 0.195, replica=1)
    tr.span("respond", b + 0.195, b + 0.2)
    tr.finish("ok", b + 0.2)
    ring.add(tr)
    return ring


def test_summarize_request_events_names_dominant_phase():
    s = summarize_request_events(_synthetic_ring().snapshot_events()
                                 ["traceEvents"])
    assert s["n_traces"] == 10
    assert s["by_outcome"] == {"ok": 10}
    assert s["by_task"] == {"ner": 10}
    assert s["phases"]["compute"]["count"] == 10
    assert s["total_ms"]["p50"] == pytest.approx(10.0, rel=0.01)
    p99 = s["p99"]
    assert p99["dominant_phase"] == "queue_wait"
    assert p99["dominant_share"] > 0.5
    assert p99["replica"] == "r1"
    assert p99["n_traces"] >= 1


def test_trace_summary_cli_requests_mode(tmp_path):
    path = tmp_path / "traces.json"
    path.write_text(json.dumps(_synthetic_ring().snapshot_events()))
    out_json = tmp_path / "summary.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_summary.py"),
         "--requests", "--trace", str(path), "--json", str(out_json)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "p99 is" in proc.stdout and "queue_wait" in proc.stdout
    saved = json.loads(out_json.read_text())
    assert saved["p99"]["dominant_phase"] == "queue_wait"


# -- cost accounting -----------------------------------------------------------


def test_scheduler_device_seconds_and_cost_metrics():
    sch = Scheduler(_EchoEngine(), packing=True, batch_wait_ms=0.0,
                    cost_per_device_hour=2.0).start()
    try:
        h = sch.submit("ner", np.arange(8, dtype=np.int32))
        sch.result(h, timeout=30)
    finally:
        sch.close()
    dev = sch.registry.counter(
        "bert_serve_device_seconds_total",
        labels=("task",)).value(task="ner")
    assert dev > 0
    cost = sch.registry.gauge(
        "bert_serve_cost_per_1k_tokens",
        labels=("task",)).value(task="ner")
    # cumulative: dev device-seconds at 2.0/hour over 8 real tokens
    assert cost == pytest.approx(dev / 3600.0 * 2.0 / (8 / 1000.0))
    assert sch.registry.gauge(
        "bert_serve_cost_per_device_hour").value() == 2.0
    # the compute spans' pro-rated shares sum back to the wave total
    shares = [attrs["device_seconds"]
              for t in sch.trace_ring.traces()
              for name, _, _, attrs in t.spans if name == "compute"]
    assert sum(shares) == pytest.approx(dev, rel=1e-6)


def test_resolve_cost_per_device_hour(monkeypatch):
    assert resolve_cost_per_device_hour(2.5) == 2.5
    monkeypatch.setenv("BERT_COST_PER_DEVICE_HOUR", "4.25")
    assert resolve_cost_per_device_hour(None) == 4.25
    assert resolve_cost_per_device_hour(0.5) == 0.5  # explicit beats env
    monkeypatch.setenv("BERT_COST_PER_DEVICE_HOUR", "bogus")
    assert resolve_cost_per_device_hour(None) == 1.0
    monkeypatch.delenv("BERT_COST_PER_DEVICE_HOUR")
    assert resolve_cost_per_device_hour(None) == 1.0


def test_stepwatch_perf_record_cost_fields():
    clock = [0.0]
    sw = StepWatch(flops_per_step=1e9, seqs_per_step=8, seq_len=64,
                   peak_flops=1e12, log_freq=2, time_fn=lambda: clock[0],
                   n_devices=4, cost_per_device_hour=3.6)
    rec = None
    for _ in range(2):
        clock[0] += 0.5
        rec = sw.step_done()
    assert rec is not None
    # 2 steps in 1.0s wall x 4 devices = 4.0 device-seconds
    assert rec["device_seconds_per_step"] == pytest.approx(2.0)
    # cost 4.0/3600*3.6 over 8*2*64 = 1024 slot tokens
    assert rec["cost_per_1k_tokens"] == pytest.approx(
        4.0 / 3600.0 * 3.6 / 1.024)


def test_stepwatch_cost_uses_real_tokens_when_noted():
    clock = [0.0]
    sw = StepWatch(flops_per_step=1e9, seqs_per_step=8, seq_len=64,
                   peak_flops=1e12, log_freq=1, time_fn=lambda: clock[0],
                   n_devices=1, cost_per_device_hour=3600.0)
    sw.note_tokens(256)
    clock[0] += 1.0
    rec = sw.step_done()
    assert rec is not None
    # 1.0 device-second at 3600/hour = 1.0 over 256 real tokens
    assert rec["cost_per_1k_tokens"] == pytest.approx(1.0 / 0.256)


# -- satellite: queue-depth gauge freshness -----------------------------------


def test_replica_queue_depth_gauge_fresh_on_enqueue():
    """The gauge must move on ENQUEUE while the worker is jammed — the
    staleness bug was publishing only on pop, so a stuck replica looked
    empty exactly when its queue was deepest."""
    gate = threading.Event()
    sch = Scheduler(_EchoEngine(gate=gate), packing=True,
                    batch_wait_ms=0.0).start()
    g = sch.registry.gauge("bert_serve_replica_queue_depth",
                           labels=("replica",))
    try:
        ids = np.arange(8, dtype=np.int32)
        h1 = sch.submit("ner", ids)
        deadline = time.time() + 30
        while not sch._inflight[0] and time.time() < deadline:
            time.sleep(0.005)
        assert sch._inflight[0] == 1      # worker jammed on wave 1
        later = [sch.submit("ner", ids) for _ in range(3)]
        while g.value(replica="0") < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert g.value(replica="0") >= 1, \
            "queue-depth gauge stale while waves queued behind a jam"
        gate.set()
        for h in [h1] + later:
            sch.result(h, timeout=30)
    finally:
        gate.set()
        sch.close()
    assert g.value(replica="0") == 0


# -- trace-id handoff ----------------------------------------------------------


def test_collect_trace_ids_thread_local_scope():
    note_trace_id("outside")              # no scope open: no-op
    with collect_trace_ids() as ids:
        note_trace_id("a")
        with collect_trace_ids() as inner:
            note_trace_id("b")
        assert inner == ["b"]
        note_trace_id("c")
    assert ids == ["a", "c"]

    seen = {}

    def other():
        with collect_trace_ids() as tids:
            seen["other"] = tids
            time.sleep(0.05)

    t = threading.Thread(target=other)
    with collect_trace_ids() as mine:
        t.start()
        note_trace_id("mine-only")
        t.join()
    assert mine == ["mine-only"]
    assert seen["other"] == []


def test_submit_notes_trace_id_into_open_scope():
    sch = Scheduler(_EchoEngine(), packing=True, batch_wait_ms=0.0).start()
    try:
        with collect_trace_ids() as ids:
            h = sch.submit("ner", np.arange(6, dtype=np.int32))
        sch.result(h, timeout=30)
    finally:
        sch.close()
    assert ids == [h.trace.trace_id]
