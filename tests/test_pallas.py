"""Pallas kernel tests in interpret mode: fused LayerNorm fwd/bwd vs XLA
reference, flash attention fwd/bwd vs plain softmax attention, dropout mask
consistency, multi-tensor l2norm/scale/clip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.ops.layernorm import _layer_norm_xla
from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention
from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas


# -- layernorm --------------------------------------------------------------

def test_layernorm_pallas_forward_matches_xla():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 300, 256).astype(np.float32)  # rows not divisible: pad path
    scale = rng.randn(256).astype(np.float32)
    bias = rng.randn(256).astype(np.float32)
    got = layer_norm_pallas(jnp.array(x), jnp.array(scale), jnp.array(bias),
                            1e-12, True)
    want = _layer_norm_xla(jnp.array(x), jnp.array(scale), jnp.array(bias),
                           1e-12)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_layernorm_pallas_grads_match_xla():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 128, 256).astype(np.float32)
    scale = rng.randn(256).astype(np.float32)
    bias = rng.randn(256).astype(np.float32)

    def loss_pallas(x, s, b):
        return jnp.sum(jnp.sin(layer_norm_pallas(x, s, b, 1e-12, True)))

    def loss_xla(x, s, b):
        return jnp.sum(jnp.sin(_layer_norm_xla(x, s, b, 1e-12)))

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(
        jnp.array(x), jnp.array(scale), jnp.array(bias))
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(
        jnp.array(x), jnp.array(scale), jnp.array(bias))
    for a, b_ in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_layernorm_pallas_bf16_dtype_preserved():
    x = jnp.ones((8, 256), jnp.bfloat16)
    s = jnp.ones((256,), jnp.float32)
    b = jnp.zeros((256,), jnp.float32)
    y = layer_norm_pallas(x, s, b, 1e-12, True)
    assert y.dtype == jnp.bfloat16


# -- fused residual + dropout + LayerNorm -----------------------------------

from bert_pytorch_tpu.ops.layernorm import (_add_dropout_layer_norm_xla,
                                            _hash_keep_mask)
from bert_pytorch_tpu.ops.pallas.layernorm import (
    add_dropout_layer_norm_pallas)


def test_adln_rate0_equals_plain_layernorm():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 200, 256).astype(np.float32)  # pad path
    res = rng.randn(2, 200, 256).astype(np.float32)
    s = rng.randn(256).astype(np.float32)
    b = rng.randn(256).astype(np.float32)
    got = add_dropout_layer_norm_pallas(
        jnp.array(x), jnp.array(res), jnp.array(s), jnp.array(b),
        jnp.int32(7), 0.0, 1e-12, True)
    want = _layer_norm_xla(jnp.array(res + x), jnp.array(s), jnp.array(b),
                           1e-12)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_adln_kernel_matches_xla_mirror_bitmask():
    """The Pallas kernel and the XLA fallback must drop the SAME units
    (identical counter-hash mask) and produce matching outputs."""
    rng = np.random.RandomState(1)
    x = rng.randn(4, 64, 256).astype(np.float32)
    res = rng.randn(4, 64, 256).astype(np.float32)
    s = rng.randn(256).astype(np.float32)
    b = rng.randn(256).astype(np.float32)
    for seed in (0, 123, -5):
        got = add_dropout_layer_norm_pallas(
            jnp.array(x), jnp.array(res), jnp.array(s), jnp.array(b),
            jnp.int32(seed), 0.1, 1e-12, True)
        want = _add_dropout_layer_norm_xla(
            jnp.array(x), jnp.array(res), jnp.array(s), jnp.array(b),
            jnp.int32(seed), 0.1, 1e-12)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_adln_grads_match_xla_mirror():
    """custom_vjp backward (mask regenerated in-kernel) vs autodiff of the
    XLA mirror that materializes the same mask."""
    rng = np.random.RandomState(2)
    x = rng.randn(2, 128, 256).astype(np.float32)
    res = rng.randn(2, 128, 256).astype(np.float32)
    s = rng.randn(256).astype(np.float32)
    b = rng.randn(256).astype(np.float32)
    seed = jnp.int32(99)

    def loss_pallas(x, res, s, b):
        return jnp.sum(jnp.sin(add_dropout_layer_norm_pallas(
            x, res, s, b, seed, 0.1, 1e-12, True)))

    def loss_xla(x, res, s, b):
        return jnp.sum(jnp.sin(_add_dropout_layer_norm_xla(
            x, res, s, b, seed, 0.1, 1e-12)))

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2, 3))(
        jnp.array(x), jnp.array(res), jnp.array(s), jnp.array(b))
    gx = jax.grad(loss_xla, argnums=(0, 1, 2, 3))(
        jnp.array(x), jnp.array(res), jnp.array(s), jnp.array(b))
    for a, b_ in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_adln_mask_statistics():
    """Keep rate ~= 1-p; different seeds draw different masks."""
    m1 = np.asarray(_hash_keep_mask(jnp.int32(1), (512, 256), 0.1))
    m2 = np.asarray(_hash_keep_mask(jnp.int32(2), (512, 256), 0.1))
    assert abs(m1.mean() - 0.9) < 5e-3
    assert abs(m2.mean() - 0.9) < 5e-3
    assert 0.17 < (m1 != m2).mean() < 0.19  # 2*p*(1-p) = 0.18 if independent
    # dropped units are scaled by exactly 1/(1-p)
    x = np.ones((512, 256), np.float32)
    seed = jnp.int32(1)
    # bypass LN: recover dropout output via h = residual + dropout(x) with
    # scale chosen to make LN identity is fiddly; instead check the mask
    # applied inside the XLA mirror directly
    keep = np.asarray(_hash_keep_mask(seed, x.shape, 0.1))
    dropped = np.where(keep, x / 0.9, 0.0)
    assert np.allclose(np.unique(dropped), [0.0, 1.0 / 0.9])


def test_adln_bf16_dtype_preserved():
    x = jnp.ones((8, 256), jnp.bfloat16)
    res = jnp.ones((8, 256), jnp.bfloat16)
    s = jnp.ones((256,), jnp.float32)
    b = jnp.zeros((256,), jnp.float32)
    y = add_dropout_layer_norm_pallas(x, res, s, b, jnp.int32(3), 0.1,
                                      1e-12, True)
    assert y.dtype == jnp.bfloat16


def test_hash_dropout_grads_match_materialized_mask():
    """hash_dropout's custom backward (mask regenerated from the seed) must
    equal autodiff of the same mask applied via where()."""
    from bert_pytorch_tpu.ops.attention import hash_dropout
    from bert_pytorch_tpu.ops.layernorm import row_col_keep

    rng = np.random.RandomState(3)
    x = jnp.array(rng.randn(4, 8, 16, 128).astype(np.float32))
    seed = jnp.int32(42)
    rate = 0.1

    y = hash_dropout(x, seed, rate)
    keep = row_col_keep(seed, 0, 4 * 8 * 16, 128, rate).reshape(x.shape)
    want = jnp.where(keep, x / (1 - rate), 0.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-6)
    # keep statistics
    assert abs(np.asarray(keep).mean() - 0.9) < 2e-2

    g1 = jax.grad(lambda a: jnp.sum(jnp.sin(hash_dropout(a, seed, rate))))(x)
    g2 = jax.grad(lambda a: jnp.sum(jnp.sin(
        jnp.where(keep, a / (1 - rate), 0.0))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5,
                               atol=1e-6)


# -- flash attention --------------------------------------------------------

def _ref_attention(q, k, v, bias=None):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(d)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def _qkv(b=2, s=256, h=4, d=64, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.array(rng.randn(b, s, h, d).astype(np.float32)) * 0.5
    q, k, v = mk(), mk(), mk()
    mask = np.ones((b, s), np.float32)
    mask[:, s - 17:] = 0  # padded tail
    bias = jnp.array((1.0 - mask) * -10000.0)[:, None, None, :]
    return q, k, v, bias


def test_flash_forward_matches_reference():
    q, k, v, bias = _qkv()
    got = flash_attention(q, k, v, bias=bias, interpret=True)
    want = _ref_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_forward_no_bias():
    q, k, v, _ = _qkv(s=128)
    got = flash_attention(q, k, v, interpret=True)
    want = _ref_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bwd", ["fused", "split"])
def test_flash_grads_match_reference(bwd, monkeypatch):
    # both backward paths: the fused dq/dk/dv kernel (default, S <= 2048)
    # and the split two-kernel fallback that serves longer sequences
    monkeypatch.setenv("FLASH_BWD", bwd)
    q, k, v, bias = _qkv(s=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bias=bias,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, bias) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_flash_dropout_deterministic_and_unbiased():
    q, k, v, bias = _qkv(s=128)
    seed = jnp.array(7, jnp.int32)
    o1 = flash_attention(q, k, v, bias=bias, dropout_seed=seed,
                         dropout_rate=0.3, interpret=True)
    o2 = flash_attention(q, k, v, bias=bias, dropout_seed=seed,
                         dropout_rate=0.3, interpret=True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))

    o3 = flash_attention(q, k, v, bias=bias,
                         dropout_seed=jnp.array(8, jnp.int32),
                         dropout_rate=0.3, interpret=True)
    assert not np.allclose(np.asarray(o1), np.asarray(o3))

    # expectation over seeds approximates the undropped output
    outs = [np.asarray(flash_attention(
        q, k, v, bias=bias, dropout_seed=jnp.array(s_, jnp.int32),
        dropout_rate=0.3, interpret=True)) for s_ in range(24)]
    mean = np.mean(outs, axis=0)
    want = np.asarray(_ref_attention(q, k, v, bias))
    err = np.abs(mean - want).mean() / (np.abs(want).mean() + 1e-9)
    assert err < 0.15, err


@pytest.mark.parametrize("bwd", ["fused", "split"])
def test_flash_dropout_grads_flow(bwd, monkeypatch):
    """The dropout backward (masks regenerated in-kernel) must equal
    autodiff of a pure-jnp mirror applying the IDENTICAL keep mask. This
    replaces the original single-coordinate finite-difference check, which
    was fp32-noise-limited: the loss is a sum over B*S*H*D squared terms,
    so an eps=1e-3 secant carries ~1e-2 of rounding noise — 20x the true
    gradient at the probed coordinate (the analytic value is verified here
    to 1e-8 against the exact-mask mirror)."""
    from bert_pytorch_tpu.ops.pallas.flash_attention import _keep_mask

    monkeypatch.setenv("FLASH_BWD", bwd)
    b, s, h, d = 2, 128, 4, 64
    q, k, v, bias = _qkv(s=s)
    seed = jnp.array(3, jnp.int32)
    rate = 0.2

    def mirror(q, k, v):
        sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(d)
        sc = sc + bias.astype(jnp.float32)
        p = jax.nn.softmax(sc, axis=-1)
        keep = jnp.stack([jnp.stack([
            _keep_mask(seed, bi * h + hh, 0, 0, s, s, rate)
            for hh in range(h)]) for bi in range(b)])
        p = jnp.where(keep, p / (1 - rate), 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bias=bias, dropout_seed=seed,
                                       dropout_rate=rate,
                                       interpret=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(mirror(q, k, v) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(g, g_ref):
        arr = np.asarray(a)
        assert np.isfinite(arr).all() and np.abs(arr).sum() > 0
        np.testing.assert_allclose(arr, np.asarray(r), rtol=5e-4, atol=5e-5)


def test_flash_native_layout_matches_bh_layout(monkeypatch):
    """The native (B, S, H, D) kernels (default where VMEM allows) and the
    transposing (BH, S, D) grid are the SAME computation: outputs match to
    float tolerance and the dropout keep-masks are bit-identical (the
    native head loop folds batch*H + head into the hash counter — exactly
    the bh grid's program id)."""
    from bert_pytorch_tpu.ops.pallas.flash_attention import _use_native

    q, k, v, bias = _qkv(s=256)
    seed = jnp.array(11, jnp.int32)
    assert _use_native(256, 4, 64)

    def run(layout):
        monkeypatch.setenv("FLASH_LAYOUT", layout)
        out = flash_attention(q, k, v, bias=bias, interpret=True)
        drop = flash_attention(q, k, v, bias=bias, dropout_seed=seed,
                               dropout_rate=0.3, interpret=True)
        g = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, bias=bias, dropout_seed=seed, dropout_rate=0.3,
            interpret=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        return out, drop, g

    out_n, drop_n, g_n = run("native")
    out_b, drop_b, g_b = run("bh")
    np.testing.assert_allclose(np.asarray(out_n), np.asarray(out_b),
                               rtol=1e-6, atol=1e-6)
    # identical masks -> identical zero patterns, values to float tolerance
    np.testing.assert_array_equal(np.asarray(drop_n) == 0,
                                  np.asarray(drop_b) == 0)
    np.testing.assert_allclose(np.asarray(drop_n), np.asarray(drop_b),
                               rtol=1e-6, atol=1e-6)
    for a, b in zip(g_n, g_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_flash_native_gate_respects_vmem_budget(monkeypatch):
    from bert_pytorch_tpu.ops.pallas.flash_attention import _use_native

    monkeypatch.delenv("FLASH_LAYOUT", raising=False)
    monkeypatch.delenv("FLASH_BWD", raising=False)
    assert _use_native(512, 16, 64)        # BERT-Large phase 2: fits
    assert not _use_native(2048, 16, 64)   # long context: transpose path
    monkeypatch.setenv("FLASH_BWD", "split")  # split kernels are bh-only
    assert not _use_native(512, 16, 64)


# -- multi-tensor -----------------------------------------------------------
#
# The fused LAMB update (round 16, ops/pallas/fused_optim.py — the apex
# FusedLAMB / amp_C multi-tensor analogue). Parity tiers are deliberate:
# the XLA fallback evaluates the SAME per-leaf math as the unfused
# optimizer, so it must be BIT-identical; the Pallas kernel flattens
# leaves into fixed blocks, which reassociates the odd FMA, so stage 1 is
# gated at a few ulps while stage 2 (t + ratio*u, no reduction) stays
# exact. See the module docstring for the measured ambiguity.


def _fused_fixture(seed=0):
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import (default_trust_batch_axes,
                                             default_weight_decay_mask,
                                             lamb)

    rng = np.random.RandomState(seed)
    params = {
        "layers": {"kernel": jnp.asarray(rng.randn(2, 33, 65), jnp.float32),
                   "bias": jnp.asarray(rng.randn(2, 65), jnp.float32)},
        "emb": jnp.asarray(rng.randn(100, 33), jnp.float32),
        "ln": {"scale": jnp.asarray(rng.randn(33), jnp.float32)},
    }
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32), params)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100,
                                            warmup=0.1)

    def run(**kw):
        import optax
        tx = lamb(sched, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes, **kw)
        st = tx.init(params)
        p = params
        upd_fn = jax.jit(tx.update)
        for _ in range(3):
            upd, st = upd_fn(grads, st, p)
            p = optax.apply_updates(p, upd)
        return p, st

    return run


def test_fused_lamb_xla_fallback_bit_identical():
    run = _fused_fixture()
    base_p, base_st = run()
    fp, fst = run(fused=True, fused_impl="xla")
    for what, a, b in (("params", base_p, fp), ("mu", base_st.mu, fst.mu),
                       ("nu", base_st.nu, fst.nu)):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y),
                err_msg=f"fused[xla] {what} drifted from unfused")


def test_fused_lamb_pallas_matches_within_ulps():
    run = _fused_fixture()
    base_p, base_st = run()
    fp, fst = run(fused=True, fused_impl="pallas")
    # moments come out of stage 1's elementwise EMA — no reassociation
    # crosses them, so they stay exact even from the kernel
    for what, a, b in (("mu", base_st.mu, fst.mu),
                       ("nu", base_st.nu, fst.nu)):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y),
                err_msg=f"fused[pallas] {what} drifted from unfused")
    for x, y in zip(jax.tree.leaves(base_p), jax.tree.leaves(fp)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=5e-7)


def test_fused_stage_kernels_vs_xla():
    from bert_pytorch_tpu.ops.pallas import fused_optim

    rng = np.random.RandomState(7)
    leaves = [jnp.asarray(rng.randn(3, 257), jnp.float32),
              jnp.asarray(rng.randn(5,), jnp.float32),
              jnp.asarray(rng.randn(64, 128), jnp.float32)]
    mus = [jnp.abs(jnp.asarray(rng.randn(*x.shape), jnp.float32))
           for x in leaves]
    nus = [jnp.abs(jnp.asarray(rng.randn(*x.shape), jnp.float32))
           for x in leaves]
    pfs = [jnp.asarray(rng.randn(*x.shape), jnp.float32) for x in leaves]
    wds = [0.01, 0.0, 0.01]
    outs = {}
    for impl in ("xla", "pallas"):
        outs[impl] = fused_optim.lamb_stage1(
            leaves, mus, nus, pfs, wds, denom=1.37, c1=0.9, c2=0.99,
            b1=0.9, b2=0.999, eps=1e-6, impl=impl, bucket_bytes=64 << 10)
    for ga, gb in zip(outs["xla"], outs["pallas"]):
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=5e-7)
    # stage 2 is a pure elementwise axpy — bit-exact across impls
    ts = [jnp.asarray(rng.randn(*x.shape), jnp.float32) for x in leaves]
    us = outs["xla"][2]
    o_xla = fused_optim.lamb_stage2(ts, us, impl="xla",
                                    bucket_bytes=64 << 10)
    o_pls = fused_optim.lamb_stage2(ts, us, impl="pallas",
                                    bucket_bytes=64 << 10)
    for a, b in zip(o_xla, o_pls):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


