"""Unit tests for the local-corpus harvester's extractors
(scripts/make_local_corpus.py): markdown/METADATA cleaning, C-comment
mining with license filtering, and the sentence formatter contract the
pipeline (format -> vocab -> encode) consumes."""

import os
import sys


sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import make_local_corpus as mlc  # noqa: E402


def test_clean_markdown_strips_fences_links_markup():
    text = (
        "# Title\n\n"
        "This package does useful things and has a very long descriptive "
        "opening sentence for the corpus.\n\n"
        "```python\nimport os\nos.system('rm -rf /')\n```\n\n"
        "See [the docs](https://example.com/docs) and ![badge](b.svg) "
        "for details. " + "More prose here. " * 30)
    out = mlc._clean_markdown(text)
    assert out is not None
    assert "import os" not in out
    assert "os.system" not in out
    assert "https://example.com" not in out
    assert "b.svg" not in out
    assert "the docs" in out


def test_clean_markdown_unbalanced_fence_drops_tail():
    # file truncated mid-fence: everything from the unmatched opener must go
    text = "Short intro.\n\n```python\ncode that must not leak\n" + "x " * 400
    assert mlc._clean_markdown(text) is None  # remaining prose too short
    text2 = ("Long enough opening prose sentence. " * 20
             + "\n\n```\ntruncated code " + "y " * 400)
    out = mlc._clean_markdown(text2)
    assert out is not None and "truncated code" not in out


def test_c_comment_extractor(tmp_path):
    (tmp_path / "api.h").write_text(
        "/* This header defines the frobnicator interface used by the\n"
        " * scheduler to negotiate buffer ownership across threads. */\n"
        "int frob(int x);\n"
        "// The retry loop backs off exponentially because the device\n"
        "// can stay busy for several milliseconds under load.\n"
        "int retry(void);\n"
        "/* Copyright (C) 2020 Someone. This program is free software; "
        "you can redistribute it under the GNU General Public License. */\n")
    docs = list(mlc.iter_c_comment_docs(str(tmp_path)))
    assert len(docs) == 1
    doc = docs[0]
    assert "frobnicator interface" in doc
    assert "backs off exponentially" in doc
    # the license block is filtered wherever it appears
    assert "General Public License" not in doc
    assert "Copyright" not in doc
    # the gutter (leading '*' / '//') is stripped
    assert "\n*" not in doc and "//" not in doc


def test_metadata_extractor(tmp_path):
    dist = tmp_path / "pkg-1.0.dist-info"
    dist.mkdir()
    (dist / "METADATA").write_text(
        "Metadata-Version: 2.1\nName: pkg\nVersion: 1.0\n\n"
        "# pkg\n\nA library that solves a specific problem in a clear and "
        "documented way. " + "It has many features worth describing. " * 20)
    docs = list(mlc.iter_metadata_docs(str(tmp_path)))
    assert len(docs) == 1
    assert "solves a specific problem" in docs[0]
    assert "Metadata-Version" not in docs[0]


def test_markdown_walk_prunes_vendored_only_outside_node_roots(tmp_path):
    body = ("Real prose long enough to survive the cleaning threshold. "
            * 20)
    top = tmp_path / "site-packages"
    (top / "node_modules" / "dep").mkdir(parents=True)
    (top / "node_modules" / "dep" / "README.md").write_text(body)
    (top / "pkg").mkdir()
    (top / "pkg" / "README.md").write_text(body)
    # site-packages root: vendored node_modules pruned
    assert len(list(mlc.iter_markdown_docs(str(top)))) == 1
    # a node_modules root itself (path component, like /usr/lib/node_modules):
    # nested deps are the content
    root = tmp_path / "usr_lib" / "node_modules"
    (root / "npm" / "node_modules" / "dep2").mkdir(parents=True)
    (root / "npm" / "node_modules" / "dep2" / "README.md").write_text(body)
    assert len(list(mlc.iter_markdown_docs(str(root)))) == 1
    # ...and a name merely containing the substring is NOT a node root
    backup = tmp_path / "my_node_modules_backup"
    (backup / "node_modules" / "dep3").mkdir(parents=True)
    (backup / "node_modules" / "dep3" / "README.md").write_text(body)
    assert len(list(mlc.iter_markdown_docs(str(backup)))) == 0


def test_doc_to_lines_sentence_contract():
    doc = ("The first sentence explains the module. The second sentence "
           "adds detail about behavior.\n\n"
           "    indented code block that must be dropped entirely\n"
           ">>> doctest_prompt()\n"
           "| a | table | row | that | must | go |\n")
    lines = mlc.doc_to_lines(doc)
    assert any("first sentence" in ln for ln in lines)
    assert all("indented code" not in ln for ln in lines)
    assert all("doctest_prompt" not in ln for ln in lines)
    assert all("|" not in ln for ln in lines)


def test_license_markers_case_insensitive(tmp_path):
    (tmp_path / "x.h").write_text(
        "/* Licensed under the APACHE LICENSE, Version 2.0; details follow "
        "in many words to pass the length threshold for comment blocks. */\n"
        "/* A genuinely useful comment describing the ring buffer layout "
        "and its invariants across producer and consumer threads, long "
        "enough to clear the per-document length threshold on its own "
        "after the license block above has been filtered away. */\n")
    docs = list(mlc.iter_c_comment_docs(str(tmp_path)))
    assert len(docs) == 1
    assert "APACHE" not in docs[0]
    assert "ring buffer layout" in docs[0]
