"""Task-registry tests: the TaskSpec contract (featurize -> forward ->
decode on a tiny fixture, through the real engine + scheduler +
service), registry coverage invariants (every task has a loadtest
payload and a serving route), and the segment-kind demux bit-identity
pin for pooled heads."""

import importlib.util
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from bert_pytorch_tpu.tasks import registry  # noqa: E402

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + (
    "the cat sat on mat a dog did run in park who what where when how "
    "why fast slow red blue green bert serves packed rows").split()
NER_LABELS = ["B-PER", "I-PER", "B-LOC", "I-LOC", "O"]
CLASS_NAMES = ["negative", "positive"]


def test_registry_lists_at_least_five_tasks():
    tasks = registry.all_tasks()
    assert len(tasks) >= 5
    assert {"squad", "ner", "classify", "choice", "embed"} <= set(tasks)
    for name in tasks:
        spec = registry.get(name)
        assert spec.name == name
        assert spec.output_kind in ("token", "segment")
        assert callable(spec.parse_arguments)
        assert callable(spec.setup)
        assert callable(spec.build_serving_model)
        assert callable(spec.forward_builder)
        assert callable(spec.make_service)
        assert spec.request_schema, name
        assert spec.head, name


def test_loadtest_payloads_cover_every_registered_task():
    """tools/loadtest._payload must generate traffic for every task —
    otherwise a new task silently gets zero coverage in the check_serve
    mixed burst."""
    import json

    spec = importlib.util.spec_from_file_location(
        "loadtest", os.path.join(REPO, "tools", "loadtest.py"))
    lt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lt)
    schemas = {"squad": {"question", "context"}, "ner": {"tokens"},
               "classify": {"text", "text_pair"},
               "choice": {"question", "choices"},
               "embed": {"text", "texts"}}
    for task in registry.all_tasks():
        for i in range(8):
            payload = lt._payload(task, i)
            assert isinstance(payload, dict) and payload, task
            json.dumps(payload)
            assert set(payload) <= schemas[task], (task, payload)
    # weighted mix parsing ('all' expands to the whole registry)
    assert lt.parse_task_mix("squad:2,ner") == ["squad", "squad", "ner"]
    assert sorted(set(lt.parse_task_mix("all"))) == list(
        registry.all_tasks())


@pytest.fixture(scope="module")
def battery(tmp_path_factory):
    """One engine + scheduler + service per registered task, on a tiny
    shared config — the contract-test fixture."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.data.tokenization import BertWordPieceTokenizer
    from bert_pytorch_tpu.serving.batcher import Scheduler
    from bert_pytorch_tpu.serving.engine import ServingEngine
    from bert_pytorch_tpu.training.state import unbox

    vocab_path = str(tmp_path_factory.mktemp("registry_vocab")
                     / "vocab.txt")
    with open(vocab_path, "w", encoding="utf-8") as f:
        f.write("\n".join(VOCAB) + "\n")
    tokenizer = BertWordPieceTokenizer(vocab_path, lowercase=True)

    config = BertConfig(
        vocab_size=len(VOCAB), hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, fused_ops=False,
        attention_impl="xla")
    serve_opts = {"labels": NER_LABELS, "class_names": CLASS_NAMES,
                  "num_choices": 2, "embed_labels": 2, "max_segments": 4}

    forwards, params, kinds = {}, {}, {}
    sample = jnp.zeros((1, 32), jnp.int32)
    for task in registry.all_tasks():
        spec = registry.get(task)
        model = spec.build_serving_model(config, jnp.float32, serve_opts)
        params[task] = unbox(model.init(
            jax.random.PRNGKey(0), sample, sample, sample)["params"])
        forwards[task] = spec.forward_builder(model)
        kinds[task] = spec.output_kind
    engine = ServingEngine(forwards, params, buckets=(32,), batch_rows=2,
                           max_segments=4, output_kinds=kinds)
    engine.warmup()
    scheduler = Scheduler(engine, packing=True, batch_wait_ms=0.5).start()
    services = {task: registry.get(task).make_service(
        scheduler, tokenizer, serve_opts) for task in registry.all_tasks()}
    yield engine, scheduler, services
    scheduler.close()


def test_contract_roundtrip_every_task(battery):
    """The registry acceptance pin: every TaskSpec featurizes its
    request, rides the compiled forward, and decodes a task-shaped
    response — through the real scheduler path."""
    _engine, _scheduler, services = battery

    out = services["squad"]({"question": "who sat ?",
                             "context": "the cat sat on the mat"})
    assert isinstance(out["answer"], str)
    assert isinstance(out["nbest"], list) and out["nbest"]

    out = services["ner"]({"tokens": ["the", "cat", "sat"]})
    assert len(out["labels"]) == 3
    assert all(isinstance(l, str) for l in out["labels"])

    out = services["classify"]({"text": "the cat sat",
                                "text_pair": "on the mat"})
    assert out["label"] in CLASS_NAMES
    assert set(out["scores"]) == set(CLASS_NAMES)
    assert abs(sum(out["scores"].values()) - 1.0) < 1e-3

    out = services["choice"]({"question": "who sat",
                              "choices": ["the cat", "a dog did run"]})
    assert out["choice"] in (0, 1)
    assert len(out["scores"]) == 2
    assert abs(sum(out["scores"]) - 1.0) < 1e-3

    out = services["embed"]({"texts": ["the cat sat", "a dog did run"]})
    assert len(out["embeddings"]) == 2
    assert out["dim"] == 32
    for emb in out["embeddings"]:
        assert abs(np.linalg.norm(emb) - 1.0) < 1e-3
    single = services["embed"]({"text": "the cat sat"})
    assert single["embedding"] == single["embeddings"][0]


def test_segment_demux_packed_bit_identical(battery):
    """Pooled-head extension of the serving acceptance pin: a packed
    multi-request classify batch returns per-segment logits BIT-identical
    to the same requests served one-per-batch (the [CLS] gather is
    position-exact and cross-segment attention is exact-zero)."""
    from bert_pytorch_tpu.serving.engine import zero_batch

    engine, scheduler, _services = battery
    rng = np.random.RandomState(0)
    reqs = [rng.randint(5, len(VOCAB), (ln,)).astype(np.int32)
            for ln in (5, 9, 12)]

    singles = []
    for ids in reqs:
        batch = zero_batch(engine.batch_rows, 32)
        batch["input_ids"][0, :len(ids)] = ids
        batch["attention_mask"][0, :len(ids)] = 1
        batch["segment_ids"][0, :len(ids)] = 1
        batch["position_ids"][0, :len(ids)] = np.arange(len(ids))
        logits = engine.forward("classify", batch)
        singles.append(np.asarray(logits)[0, 0].copy())

    handles = [scheduler.submit("classify", ids) for ids in reqs]
    packed = [scheduler.result(h, timeout=60) for h in handles]
    for i, (a, b) in enumerate(zip(singles, packed)):
        assert np.array_equal(a, b), f"request {i} differs packed vs single"
    assert all(p.shape == (len(CLASS_NAMES),) for p in packed)


def test_run_server_task_checkpoint_parsing():
    """The generic --task_checkpoint TASK=DIR surface + legacy aliases
    resolve against the registry; unknown tasks fail loudly."""
    import run_server

    args = run_server.parse_arguments(
        ["--model_config_file", "cfg.json",
         "--task_checkpoint", "classify=/tmp/a",
         "--task_checkpoint", "embed=/tmp/b",
         "--squad_checkpoint", "/tmp/c"])
    assert run_server.task_checkpoints(args) == {
        "classify": "/tmp/a", "embed": "/tmp/b", "squad": "/tmp/c"}
    bad = run_server.parse_arguments(
        ["--model_config_file", "cfg.json",
         "--task_checkpoint", "nope=/tmp/x"])
    with pytest.raises(SystemExit, match="nope"):
        run_server.task_checkpoints(bad)
