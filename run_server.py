#!/usr/bin/env python
"""Inference server entry point: checkpoints -> HTTP traffic.

Assembles the serving stack (bert_pytorch_tpu/serving) from the task
registry (bert_pytorch_tpu/tasks/registry.py): every task served gets a
`POST /v1/<task>` route, an AOT-compiled bucketed forward per sequence
bucket, continuous packed batching, and the Prometheus /metrics +
/healthz on one port via telemetry.init_run(phase="serve").
docs/SERVING.md is the operator guide; tools/loadtest.py +
scripts/serve_bench.sh drive it.

    python run_server.py --model_config_file cfg.json --vocab_file vocab.txt \
        --task_checkpoint squad=out/ckpt --task_checkpoint ner=ner/ckpt \
        --task_checkpoint classify=cls/ckpt --task_checkpoint embed=emb/ckpt \
        --labels B-PER I-PER B-LOC I-LOC O --port 8000

`--squad_checkpoint` / `--ner_checkpoint` remain as aliases of the
generic `--task_checkpoint task=dir` form. `--port 0` binds an
ephemeral port; `--port_file` writes the bound port once the server is
WARM (every bucket compiled) — scripts poll that file instead of racing
the compile.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_config_file", required=True, type=str)
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--task_checkpoint", action="append", default=None,
                   metavar="TASK=DIR",
                   help="serve a registered task from an orbax checkpoint "
                        "dir (optionally dir@step); repeatable — every "
                        "TASK must exist in tasks/registry.py")
    p.add_argument("--squad_checkpoint", default=None, type=str,
                   help="alias of --task_checkpoint squad=DIR")
    p.add_argument("--ner_checkpoint", default=None, type=str,
                   help="alias of --task_checkpoint ner=DIR "
                        "(requires --labels)")
    p.add_argument("--labels", type=str, nargs="+", default=None,
                   help="NER label names (run_ner.py convention: ids "
                        "start at 1, 0 is the padding class)")
    p.add_argument("--class_names", type=str, nargs="+",
                   default=["negative", "positive"],
                   help="classify task's class names in label-id order "
                        "(sets the served head width)")
    p.add_argument("--num_choices", type=int, default=4,
                   help="choice task's training-time choice count (the "
                        "served per-segment scorer accepts any request "
                        "with 2..16 choices)")
    p.add_argument("--embed_labels", type=int, default=2,
                   help="embed task's probe-head width (must match the "
                        "checkpoint; serving returns embeddings, not "
                        "probe logits)")
    p.add_argument("--port", type=int, default=8000,
                   help="HTTP port (0 = ephemeral)")
    p.add_argument("--host", type=str, default="0.0.0.0")
    p.add_argument("--port_file", type=str, default=None,
                   help="write the bound port here once warm")
    p.add_argument("--buckets", type=str, default="64,128,256,512",
                   help="comma-separated AOT sequence-length buckets")
    p.add_argument("--batch_rows", type=int, default=8,
                   help="rows per forward batch (fixed — part of the "
                        "compiled shape)")
    p.add_argument("--max_segments", type=int, default=8,
                   help="max packed requests per row")
    p.add_argument("--packing", type=str, default="on",
                   choices=["on", "off"],
                   help="pack multiple requests per row (segment-aware "
                        "attention); off = one request per row, same "
                        "compiled program")
    p.add_argument("--serve_dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32"],
                   help="compute dtype of the served forwards (params "
                        "stay fp32)")
    p.add_argument("--queue_size", type=int, default=128,
                   help="admission queue bound; a full queue sheds with "
                        "HTTP 503")
    p.add_argument("--admission_timeout", type=float, default=10.0,
                   help="seconds a request may wait before 504")
    p.add_argument("--drain_timeout", type=float, default=30.0,
                   help="graceful-drain deadline on SIGTERM/SIGINT: "
                        "admission stops immediately (503 + Retry-After),"
                        " in-flight requests get this many seconds to "
                        "finish, metrics flush, exit 0 "
                        "(docs/RESILIENCE.md)")
    p.add_argument("--batch_wait_ms", type=float, default=2.0,
                   help="coalescing window before dispatching a batch")
    p.add_argument("--doc_stride", type=int, default=128)
    p.add_argument("--max_query_length", type=int, default=64)
    p.add_argument("--n_best_size", type=int, default=20)
    p.add_argument("--max_answer_length", type=int, default=30)
    p.add_argument("--vocab_pad_multiple", type=int, default=8,
                   help="pad the vocab like the training entry points — "
                        "checkpoints carry the padded table")
    p.add_argument("--output_dir", type=str, default=None,
                   help="optional: write serve_log jsonl/txt here")
    p.add_argument("--force_cpu", action="store_true",
                   help="force the CPU backend before jax initializes "
                        "(CI/bench harness; this box's sitecustomize "
                        "registers a remote TPU plugin, so the env var "
                        "alone is not enough — same recipe as "
                        "tests/conftest.py)")
    from bert_pytorch_tpu.config import merge_args_with_config

    return merge_args_with_config(p, argv)


def task_checkpoints(args) -> dict:
    """{task: checkpoint_dir} from --task_checkpoint entries plus the
    legacy --squad_checkpoint/--ner_checkpoint aliases, validated
    against the registry."""
    from bert_pytorch_tpu.tasks import registry

    out = {}
    for entry in args.task_checkpoint or []:
        task, sep, ckpt = entry.partition("=")
        if not sep or not task or not ckpt:
            raise SystemExit(f"--task_checkpoint wants TASK=DIR, got "
                             f"{entry!r}")
        out[task] = ckpt
    if args.squad_checkpoint:
        out.setdefault("squad", args.squad_checkpoint)
    if args.ner_checkpoint:
        out.setdefault("ner", args.ner_checkpoint)
    unknown = sorted(set(out) - set(registry.all_tasks()))
    if unknown:
        raise SystemExit(
            f"unknown task(s) {unknown}; registered: "
            + ", ".join(registry.all_tasks()))
    return out


class ServerHandle:
    """Everything `serve()` started, closable in one call (frontend first
    so no new requests land on a draining scheduler)."""

    def __init__(self, frontend, scheduler, engine, tel):
        self.frontend = frontend
        self.scheduler = scheduler
        self.engine = engine
        self.tel = tel
        self.url = frontend.url
        self.port = frontend.port

    def close(self) -> None:
        for fn in (self.frontend.close, self.scheduler.close,
                   self.tel.close):
            try:
                fn()
            except Exception:
                pass


def serve(args) -> ServerHandle:
    """Build the full stack and return a live ServerHandle (the port is
    open and every bucket is compiled when this returns)."""
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.serving.batcher import Scheduler
    from bert_pytorch_tpu.serving.engine import (ServingEngine,
                                                 restore_serving_params)
    from bert_pytorch_tpu.serving.frontend import ServingFrontend
    from bert_pytorch_tpu.tasks import registry, squad
    from bert_pytorch_tpu.telemetry import collect_provenance, init_run

    checkpoints = task_checkpoints(args)
    if not checkpoints:
        raise SystemExit(
            "nothing to serve: pass --task_checkpoint TASK=DIR (tasks: "
            + ", ".join(registry.all_tasks())
            + ") or the --squad_checkpoint/--ner_checkpoint aliases")
    if "ner" in checkpoints and not args.labels:
        raise SystemExit("serving ner requires --labels")

    log_prefix = (os.path.join(args.output_dir, "serve_log")
                  if args.output_dir else None)
    if args.output_dir:
        os.makedirs(args.output_dir, exist_ok=True)
    tel = init_run(phase="serve", log_prefix=log_prefix, jsonl=True)
    log = tel.logger.info
    tel.log_header(**collect_provenance())

    config = BertConfig.from_json_file(args.model_config_file)
    config = config.replace(
        vocab_size=pad_vocab_size(config.vocab_size,
                                  args.vocab_pad_multiple))
    vocab_file = args.vocab_file or config.vocab_file
    if not vocab_file:
        raise SystemExit("vocab_file required (CLI or model config)")
    tokenizer = get_wordpiece_tokenizer(vocab_file,
                                        uppercase=not config.lowercase)
    compute_dtype = (jnp.bfloat16 if args.serve_dtype == "bfloat16"
                     else jnp.float32)

    buckets = sorted({int(b) for b in args.buckets.split(",") if b.strip()})
    usable = [b for b in buckets if b <= config.max_position_embeddings]
    if usable != buckets:
        log(f"WARNING: dropping buckets beyond max_position_embeddings="
            f"{config.max_position_embeddings}: "
            f"{sorted(set(buckets) - set(usable))}")
    if not usable:
        raise SystemExit("no usable bucket <= max_position_embeddings")
    sample_len = min(usable[-1], config.max_position_embeddings)

    # the per-task serving options the registry specs consume
    serve_opts = {
        # ONE tokenizer instance serves every task, so every service must
        # serialize on ONE lock (frontend.py service classes)
        "tok_lock": threading.Lock(),
        "labels": args.labels,
        "class_names": args.class_names,
        "num_choices": args.num_choices,
        "embed_labels": args.embed_labels,
        "max_segments": args.max_segments,
        "doc_stride": args.doc_stride,
        "max_query_length": args.max_query_length,
        "answer_cfg": squad.AnswerConfig(
            n_best_size=args.n_best_size,
            max_answer_length=args.max_answer_length,
            do_lower_case=config.lowercase),
    }

    forwards, params, output_kinds, services_spec = {}, {}, {}, {}
    task_models = {}
    for task in sorted(checkpoints):
        spec = registry.get(task)
        model = spec.build_serving_model(config, compute_dtype, serve_opts)
        params[task], step = restore_serving_params(
            checkpoints[task], model, sample_len, log=log)
        forwards[task] = spec.forward_builder(model)
        output_kinds[task] = spec.output_kind
        services_spec[task] = step
        task_models[task] = model

    engine = ServingEngine(forwards, params, buckets=usable,
                           batch_rows=args.batch_rows,
                           max_segments=args.max_segments,
                           compile_watch=tel.compile_watch,
                           output_kinds=output_kinds)
    n = engine.warmup(log=log)
    log(f"serving: {n} bucketed program(s) compiled "
        f"(tasks {engine.tasks}, buckets {engine.buckets}, "
        f"batch_rows {engine.batch_rows}, packing {args.packing}, "
        f"dtype {args.serve_dtype})")

    scheduler = Scheduler(engine, queue_size=args.queue_size,
                          admission_timeout_s=args.admission_timeout,
                          batch_wait_ms=args.batch_wait_ms,
                          packing=(args.packing == "on"),
                          registry=tel.registry).start()

    services = {task: registry.get(task).make_service(
        scheduler, tokenizer, serve_opts) for task in sorted(checkpoints)}

    def healthz():
        h = tel.healthz()
        h.update({
            "tasks": {t: {"checkpoint_step": services_spec[t],
                          "head": registry.get(t).head,
                          "request_schema": dict(
                              registry.get(t).request_schema)}
                      for t in sorted(services_spec)},
            "buckets": list(engine.buckets),
            "packing": args.packing == "on",
            "queue_depth": int(
                scheduler.registry.gauge("bert_serve_queue_depth").value()),
        })
        return h

    frontend = ServingFrontend(services, tel.registry, healthz_fn=healthz,
                               port=args.port, host=args.host)
    log(f"serving: listening on {frontend.url} "
        f"(POST /v1/{{{','.join(sorted(services))}}}, GET /metrics, "
        f"GET /healthz)")
    return ServerHandle(frontend, scheduler, engine, tel)


def main(argv=None):
    args = parse_arguments(argv)
    if args.force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    handle = serve(args)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(handle.port))
        os.replace(tmp, args.port_file)

    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    old = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            old[sig] = signal.signal(sig, _on_signal)
        except ValueError:
            pass  # non-main thread (tests drive serve() directly instead)
    log = handle.tel.logger.info
    try:
        stop.wait()
        # graceful drain (docs/RESILIENCE.md): stop admission first —
        # new requests shed 503 + Retry-After while /metrics + /healthz
        # (now reporting draining:true) keep answering — then let the
        # in-flight requests finish, then tear down and exit 0 so the
        # orchestrator records a clean stop, not a crash
        handle.frontend.begin_drain()
        inflight = handle.frontend.inflight
        log(f"drain: admission stopped (503 + Retry-After); waiting up "
            f"to {args.drain_timeout:g}s for {inflight} in-flight "
            "request(s)")
        drained = handle.frontend.wait_idle(timeout=args.drain_timeout)
        log("drain: complete — all in-flight requests finished"
            if drained else
            f"WARNING: drain deadline ({args.drain_timeout:g}s) hit with "
            f"{handle.frontend.inflight} request(s) still in flight — "
            "closing anyway")
    finally:
        for sig, handler in old.items():
            signal.signal(sig, handler)
        # handle.close() flushes metrics sinks via tel.close()
        handle.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
