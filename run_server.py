#!/usr/bin/env python
"""Inference server entry point: checkpoints -> HTTP traffic.

Assembles the serving stack (bert_pytorch_tpu/serving) from the task
registry (bert_pytorch_tpu/tasks/registry.py): every task served gets a
`POST /v1/<task>` route, an AOT-compiled bucketed forward per sequence
bucket, continuous packed batching, and the Prometheus /metrics +
/healthz on one port via telemetry.init_run(phase="serve").
docs/SERVING.md is the operator guide; tools/loadtest.py +
scripts/serve_bench.sh drive it.

    python run_server.py --model_config_file cfg.json --vocab_file vocab.txt \
        --task_checkpoint squad=out/ckpt --task_checkpoint ner=ner/ckpt \
        --task_checkpoint classify=cls/ckpt --task_checkpoint embed=emb/ckpt \
        --labels B-PER I-PER B-LOC I-LOC O --port 8000

`--squad_checkpoint` / `--ner_checkpoint` remain as aliases of the
generic `--task_checkpoint task=dir` form. `--port 0` binds an
ephemeral port; `--port_file` writes the bound port once the server is
WARM (every bucket compiled) — scripts poll that file instead of racing
the compile.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_config_file", required=True, type=str)
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--task_checkpoint", action="append", default=None,
                   metavar="TASK=DIR",
                   help="serve a registered task from an orbax checkpoint "
                        "dir (optionally dir@step); repeatable — every "
                        "TASK must exist in tasks/registry.py")
    p.add_argument("--squad_checkpoint", default=None, type=str,
                   help="alias of --task_checkpoint squad=DIR")
    p.add_argument("--ner_checkpoint", default=None, type=str,
                   help="alias of --task_checkpoint ner=DIR "
                        "(requires --labels)")
    p.add_argument("--labels", type=str, nargs="+", default=None,
                   help="NER label names (run_ner.py convention: ids "
                        "start at 1, 0 is the padding class)")
    p.add_argument("--class_names", type=str, nargs="+",
                   default=["negative", "positive"],
                   help="classify task's class names in label-id order "
                        "(sets the served head width)")
    p.add_argument("--num_choices", type=int, default=4,
                   help="choice task's training-time choice count (the "
                        "served per-segment scorer accepts any request "
                        "with 2..16 choices)")
    p.add_argument("--embed_labels", type=int, default=2,
                   help="embed task's probe-head width (must match the "
                        "checkpoint; serving returns embeddings, not "
                        "probe logits)")
    p.add_argument("--port", type=int, default=8000,
                   help="HTTP port (0 = ephemeral)")
    p.add_argument("--host", type=str, default="0.0.0.0")
    p.add_argument("--port_file", type=str, default=None,
                   help="write the bound port here once warm")
    p.add_argument("--buckets", type=str, default="64,128,256,512",
                   help="comma-separated AOT sequence-length buckets")
    p.add_argument("--batch_rows", type=int, default=8,
                   help="rows per forward batch (fixed — part of the "
                        "compiled shape)")
    p.add_argument("--max_segments", type=int, default=8,
                   help="max packed requests per row")
    p.add_argument("--packing", type=str, default="on",
                   choices=["on", "off"],
                   help="pack multiple requests per row (segment-aware "
                        "attention); off = one request per row, same "
                        "compiled program")
    p.add_argument("--serve_dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32", "int8"],
                   help="compute dtype of the served forwards. bfloat16/"
                        "float32: params stay fp32. int8: symmetric "
                        "per-channel WEIGHT quantization at restore time "
                        "(serving/quantize.py) — weights live int8 in "
                        "device memory, dequantize in-graph, activations "
                        "compute in bf16; refuses to serve past "
                        "--int8_max_delta vs the f32 decode")
    p.add_argument("--int8_max_delta", type=float, default=0.1,
                   help="int8 accuracy gate: max relative decode delta vs "
                        "the f32 reference forward, per task "
                        "(tools/quantcheck.py is the offline check)")
    p.add_argument("--serve_replicas", type=int, default=1,
                   help="data-parallel replica engines over disjoint "
                        "device slices, fed by a work-stealing dispatcher "
                        "(saturation req/s scales ~linearly)")
    p.add_argument("--serve_mesh", type=str, default=None,
                   metavar="AXIS=K[,AXIS=K]",
                   help="shard each replica's engine over a device mesh, "
                        "e.g. model=2 — param shardings derive from the "
                        "logical-axis-rules table (parallel/rules.py); "
                        "each replica then occupies K devices")
    p.add_argument("--queue_size", type=int, default=128,
                   help="admission queue bound; a full queue sheds with "
                        "HTTP 503")
    p.add_argument("--admission_timeout", type=float, default=10.0,
                   help="seconds a request may wait before 504")
    p.add_argument("--drain_timeout", type=float, default=30.0,
                   help="graceful-drain deadline on SIGTERM/SIGINT: "
                        "admission stops immediately (503 + Retry-After),"
                        " in-flight requests get this many seconds to "
                        "finish, metrics flush, exit 0 "
                        "(docs/RESILIENCE.md)")
    p.add_argument("--batch_wait_ms", type=float, default=2.0,
                   help="coalescing window before dispatching a batch")
    p.add_argument("--request_tracing", type=str, default="on",
                   choices=["on", "off"],
                   help="per-request span timelines (X-Trace-Id header + "
                        "GET /v1/traces; docs/OBSERVABILITY.md). Host-side "
                        "only — cannot affect responses; off exists for "
                        "the A/B overhead measurement")
    p.add_argument("--trace_ring_slowest", type=int, default=32,
                   help="trace ring: keep the N slowest request traces "
                        "per rotating window")
    p.add_argument("--trace_ring_sample_every", type=int, default=16,
                   help="trace ring: also keep every K-th trace as a "
                        "healthy-baseline cross-section")
    p.add_argument("--trace_ring_window_s", type=float, default=60.0,
                   help="trace ring: slowest-window rotation period "
                        "(seconds); current + previous window are served")
    p.add_argument("--cost_per_device_hour", type=float, default=None,
                   help="price per device-hour for the cost-per-1k-tokens "
                        "gauges (default: BERT_COST_PER_DEVICE_HOUR env or "
                        "1.0 = normalized device-hours)")
    p.add_argument("--slo_config", type=str, default=None,
                   help="SLO spec file (configs/slo.json): turns on the "
                        "burn-rate engine — GET /v1/alerts + /v1/slo, and "
                        "/healthz's top-level status becomes the engine's "
                        "ok|degraded|failing verdict "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--slo_eval_interval_s", type=float, default=1.0,
                   help="burn-rate engine evaluation period")
    p.add_argument("--prober", type=str, default="off",
                   choices=["on", "off"],
                   help="synthetic canary prober: a background thread "
                        "sends a known-answer request per served task "
                        "through the real HTTP frontend and verifies the "
                        "DECODED answer against the first response (the "
                        "engine is deterministic), flipping per-task "
                        "health + a page alert on drift")
    p.add_argument("--probe_interval_s", type=float, default=5.0,
                   help="seconds between canary probe rounds")
    p.add_argument("--probe_timeout_s", type=float, default=30.0,
                   help="per-probe HTTP timeout")
    p.add_argument("--slo_inject", type=str, default=None,
                   choices=["error_burst", "latency_burst",
                            "corrupt_answers"],
                   help="chaos drill for scripts/check_slo.sh: wrap the "
                        "engines' forward host-side AFTER warmup so the "
                        "named fault starts at --slo_inject_after_s and "
                        "the matching alert must fire within one fast "
                        "window (compiled programs stay untouched)")
    p.add_argument("--slo_inject_after_s", type=float, default=2.0,
                   help="seconds of clean serving before the injected "
                        "fault activates (lets the prober pin baselines)")
    p.add_argument("--slo_inject_task", type=str, default=None,
                   help="restrict corrupt_answers to one task (proves the "
                        "prober localizes: only that task flips unhealthy)")
    p.add_argument("--slo_inject_latency_ms", type=float, default=400.0,
                   help="latency_burst: added host-side delay per forward")
    p.add_argument("--doc_stride", type=int, default=128)
    p.add_argument("--max_query_length", type=int, default=64)
    p.add_argument("--n_best_size", type=int, default=20)
    p.add_argument("--max_answer_length", type=int, default=30)
    p.add_argument("--vocab_pad_multiple", type=int, default=8,
                   help="pad the vocab like the training entry points — "
                        "checkpoints carry the padded table")
    p.add_argument("--output_dir", type=str, default=None,
                   help="optional: write serve_log jsonl/txt here")
    p.add_argument("--force_cpu", action="store_true",
                   help="force the CPU backend before jax initializes "
                        "(CI/bench harness; this box's sitecustomize "
                        "registers a remote TPU plugin, so the env var "
                        "alone is not enough — same recipe as "
                        "tests/conftest.py)")
    from bert_pytorch_tpu.config import merge_args_with_config

    return merge_args_with_config(p, argv)


def parse_serve_mesh(spec) -> dict:
    """'model=2' / 'model=2,seq=1' -> {"model": 2, ...}; None/'' -> {}.
    Axis names must come from the rules table's MESH_AXES (validated
    lazily in serve() against parallel.rules to stay jax-free here)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        axis, sep, k = part.partition("=")
        if not sep or not axis or not k.lstrip("-").isdigit():
            raise SystemExit(f"--serve_mesh wants AXIS=K[,AXIS=K], got "
                             f"{spec!r}")
        out[axis] = int(k)
        if out[axis] < 1:
            raise SystemExit(f"--serve_mesh {axis}={k}: K must be >= 1")
    return out


def _mesh_slice_size(mesh_axes: dict) -> int:
    n = 1
    for v in mesh_axes.values():
        n *= int(v)
    return n


def task_checkpoints(args) -> dict:
    """{task: checkpoint_dir} from --task_checkpoint entries plus the
    legacy --squad_checkpoint/--ner_checkpoint aliases, validated
    against the registry."""
    from bert_pytorch_tpu.tasks import registry

    out = {}
    for entry in args.task_checkpoint or []:
        task, sep, ckpt = entry.partition("=")
        if not sep or not task or not ckpt:
            raise SystemExit(f"--task_checkpoint wants TASK=DIR, got "
                             f"{entry!r}")
        out[task] = ckpt
    if args.squad_checkpoint:
        out.setdefault("squad", args.squad_checkpoint)
    if args.ner_checkpoint:
        out.setdefault("ner", args.ner_checkpoint)
    unknown = sorted(set(out) - set(registry.all_tasks()))
    if unknown:
        raise SystemExit(
            f"unknown task(s) {unknown}; registered: "
            + ", ".join(registry.all_tasks()))
    return out


class ServerHandle:
    """Everything `serve()` started, closable in one call (frontend first
    so no new requests land on a draining scheduler)."""

    def __init__(self, frontend, scheduler, engine, tel, slo=None,
                 prober=None, evaluator=None, injector=None):
        self.frontend = frontend
        self.scheduler = scheduler
        self.engine = engine
        self.engines = getattr(scheduler, "engines", [engine])
        self.tel = tel
        self.slo = slo
        self.prober = prober
        self.evaluator = evaluator
        self.injector = injector
        self.url = frontend.url
        self.port = frontend.port

    def close(self) -> None:
        # prober first (or it logs connection errors against the port the
        # frontend is about to release), then frontend so no new requests
        # land on a draining scheduler
        closers = []
        if self.prober is not None:
            closers.append(self.prober.close)
        closers.append(self.frontend.close)
        if self.evaluator is not None:
            closers.append(self.evaluator.close)
        closers += [self.scheduler.close, self.tel.close]
        for fn in closers:
            try:
                fn()
            except Exception:
                pass


def serve(args) -> ServerHandle:
    """Build the full stack and return a live ServerHandle (the port is
    open and every bucket is compiled when this returns)."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.parallel import rules as rules_lib
    from bert_pytorch_tpu.parallel.mesh import make_mesh
    from bert_pytorch_tpu.serving import quantize as quant_lib
    from bert_pytorch_tpu.serving.batcher import Scheduler
    from bert_pytorch_tpu.serving.engine import (ServingEngine,
                                                 restore_serving_params,
                                                 serving_param_shardings)
    from bert_pytorch_tpu.serving.frontend import ServingFrontend
    from bert_pytorch_tpu.tasks import registry, squad
    from bert_pytorch_tpu.telemetry import collect_provenance, init_run

    checkpoints = task_checkpoints(args)
    mesh_axes = parse_serve_mesh(getattr(args, "serve_mesh", None))
    bad_axes = sorted(set(mesh_axes) - set(rules_lib.MESH_AXES))
    if bad_axes:
        raise SystemExit(f"--serve_mesh axes {bad_axes} not in the rules "
                         f"table's {list(rules_lib.MESH_AXES)}")
    mesh_size = _mesh_slice_size(mesh_axes)
    replicas = max(1, int(getattr(args, "serve_replicas", 1) or 1))
    if args.serve_dtype == "int8" and mesh_size > 1:
        raise SystemExit(
            "--serve_dtype int8 with --serve_mesh is not supported: the "
            "quantized param tree carries {q8, scale} dict leaves the "
            "rules table has no logical annotations for (docs/SERVING.md)"
            " — pick one lever, or scale out with --serve_replicas")
    devices = jax.devices()
    need = replicas * mesh_size
    if len(devices) < need:
        raise SystemExit(
            f"--serve_replicas {replicas} x mesh slice {mesh_size} needs "
            f"{need} device(s), have {len(devices)} (with --force_cpu the "
            "launcher forces a matching host device count automatically)")
    if not checkpoints:
        raise SystemExit(
            "nothing to serve: pass --task_checkpoint TASK=DIR (tasks: "
            + ", ".join(registry.all_tasks())
            + ") or the --squad_checkpoint/--ner_checkpoint aliases")
    if "ner" in checkpoints and not args.labels:
        raise SystemExit("serving ner requires --labels")

    log_prefix = (os.path.join(args.output_dir, "serve_log")
                  if args.output_dir else None)
    if args.output_dir:
        os.makedirs(args.output_dir, exist_ok=True)
    tel = init_run(phase="serve", log_prefix=log_prefix, jsonl=True)
    log = tel.logger.info
    tel.log_header(**collect_provenance())

    config = BertConfig.from_json_file(args.model_config_file)
    config = config.replace(
        vocab_size=pad_vocab_size(config.vocab_size,
                                  args.vocab_pad_multiple))
    vocab_file = args.vocab_file or config.vocab_file
    if not vocab_file:
        raise SystemExit("vocab_file required (CLI or model config)")
    tokenizer = get_wordpiece_tokenizer(vocab_file,
                                        uppercase=not config.lowercase)
    # int8 is WEIGHT-only quantization — activations compute in bf16
    compute_dtype = (jnp.float32 if args.serve_dtype == "float32"
                     else jnp.bfloat16)

    buckets = sorted({int(b) for b in args.buckets.split(",") if b.strip()})
    usable = [b for b in buckets if b <= config.max_position_embeddings]
    if usable != buckets:
        log(f"WARNING: dropping buckets beyond max_position_embeddings="
            f"{config.max_position_embeddings}: "
            f"{sorted(set(buckets) - set(usable))}")
    if not usable:
        raise SystemExit("no usable bucket <= max_position_embeddings")
    sample_len = min(usable[-1], config.max_position_embeddings)

    # the per-task serving options the registry specs consume
    serve_opts = {
        # ONE tokenizer instance serves every task, so every service must
        # serialize on ONE lock (frontend.py service classes)
        "tok_lock": threading.Lock(),
        "labels": args.labels,
        "class_names": args.class_names,
        "num_choices": args.num_choices,
        "embed_labels": args.embed_labels,
        "max_segments": args.max_segments,
        "doc_stride": args.doc_stride,
        "max_query_length": args.max_query_length,
        "answer_cfg": squad.AnswerConfig(
            n_best_size=args.n_best_size,
            max_answer_length=args.max_answer_length,
            do_lower_case=config.lowercase),
    }

    forwards, params, output_kinds, services_spec = {}, {}, {}, {}
    task_models, model_params_count = {}, {}
    # fleet dashboards correlate cost_per_1k_tokens with model size
    # (teacher vs distilled student checkpoints serve through the same
    # stack) — export the served parameter count per task
    params_gauge = tel.registry.gauge(
        "bert_serve_model_params",
        "parameters served per task (model size)", labels=("task",))
    for task in sorted(checkpoints):
        spec = registry.get(task)
        model = spec.build_serving_model(config, compute_dtype, serve_opts)
        params[task], step = restore_serving_params(
            checkpoints[task], model, sample_len, log=log)
        forwards[task] = spec.forward_builder(model)
        output_kinds[task] = spec.output_kind
        services_spec[task] = step
        task_models[task] = model
        model_params_count[task] = sum(
            int(leaf.size)
            for leaf in jax.tree_util.tree_leaves(params[task]))
        params_gauge.set(model_params_count[task], task=task)

    int8_deltas = {}
    if args.serve_dtype == "int8":
        # quantize ONCE host-side; gate each task's decode against the
        # f32 reference before a single request is admitted — serving a
        # silently broken quantization is an outage, not a warning
        probe = quant_lib.probe_batch(
            min(2, args.batch_rows), usable[0], config.vocab_size,
            max_segments=min(2, args.max_segments))
        for task in sorted(checkpoints):
            qparams, stats = quant_lib.quantize_tree(
                jax.device_get(params[task]))
            spec = registry.get(task)
            ref_model = spec.build_serving_model(config, jnp.float32,
                                                 serve_opts)
            ref_forward = spec.forward_builder(ref_model)
            q_forward = quant_lib.wrap_forward(forwards[task],
                                               compute_dtype)
            delta = quant_lib.decode_delta(ref_forward, params[task],
                                           q_forward, qparams, probe)
            int8_deltas[task] = delta
            log(f"int8[{task}]: {stats['quantized_leaves']} leaves "
                f"quantized ({stats['bytes_before'] / 1e6:.1f} -> "
                f"{stats['bytes_after'] / 1e6:.1f} MB), rel_delta "
                f"{delta['rel_delta']:.4f}, argmax_agreement "
                f"{delta['argmax_agreement']:.4f}")
            if delta["rel_delta"] > args.int8_max_delta:
                raise SystemExit(
                    f"int8 accuracy gate: task {task!r} rel decode delta "
                    f"{delta['rel_delta']:.4f} exceeds --int8_max_delta "
                    f"{args.int8_max_delta:g}; refusing to serve "
                    "(tools/quantcheck.py to inspect offline)")
            params[task] = qparams
            forwards[task] = q_forward

    engines = []
    n = 0
    for i in range(replicas):
        dev_slice = devices[i * mesh_size:(i + 1) * mesh_size]
        mesh_i = make_mesh(dict(mesh_axes) or None, devices=dev_slice)
        shardings_i = None
        if mesh_size > 1:
            shardings_i = {
                t: serving_param_shardings(task_models[t], sample_len,
                                           mesh_i)[0]
                for t in sorted(checkpoints)}
        eng = ServingEngine(forwards, params, buckets=usable,
                            batch_rows=args.batch_rows,
                            max_segments=args.max_segments,
                            compile_watch=tel.compile_watch,
                            output_kinds=output_kinds,
                            mesh=mesh_i, param_shardings=shardings_i,
                            name=f"r{i}")
        # steady-state arms ONCE after every replica warmed up: arming
        # per-engine would flag replica K>0's warmup compiles as loud
        # RECOMPILEs (the bug this replaced)
        n += eng.warmup(log=log, mark_steady=False)
        engines.append(eng)
    if tel.compile_watch is not None:
        tel.compile_watch.mark_steady()
    engine = engines[0]
    log(f"serving: {n} bucketed program(s) compiled across "
        f"{replicas} replica(s) "
        f"(tasks {engine.tasks}, buckets {engine.buckets}, "
        f"batch_rows {engine.batch_rows}, packing {args.packing}, "
        f"dtype {args.serve_dtype}"
        + (f", mesh {mesh_axes}" if mesh_size > 1 else "") + ")")

    injector = None
    if getattr(args, "slo_inject", None):
        # chaos drill: wrap forward HOST-side after warmup — wrapping the
        # python callables before engine construction would be traced
        # into the AOT programs and compiled out
        from bert_pytorch_tpu.telemetry.slo import FaultInjector

        injector = FaultInjector(
            args.slo_inject,
            after_s=getattr(args, "slo_inject_after_s", 2.0),
            task=getattr(args, "slo_inject_task", None),
            latency_ms=getattr(args, "slo_inject_latency_ms", 400.0))
        for eng in engines:
            injector.install(eng)
        log(f"slo_inject: {args.slo_inject} arms "
            f"{args.slo_inject_after_s:g}s after warmup"
            + (f" (task {args.slo_inject_task})"
               if args.slo_inject_task else ""))

    # scale the batching window with the fleet size: N replicas consume
    # waves N× faster, so an unscaled window would freeze each wave with
    # 1/N the coalesced requests — every wave still costs the full padded
    # batch_rows x bucket compute, and the shallower packs would burn the
    # whole scale-out win (measured on the CPU harness: 2 replicas at the
    # single-replica window saturate ~25% EARLIER than one replica)
    tracing = getattr(args, "request_tracing", "on") == "on"
    trace_ring = None
    if tracing:
        from bert_pytorch_tpu.serving.request_trace import TraceRing

        trace_ring = TraceRing(
            keep_slowest=getattr(args, "trace_ring_slowest", 32),
            sample_every=getattr(args, "trace_ring_sample_every", 16),
            window_s=getattr(args, "trace_ring_window_s", 60.0))
    scheduler = Scheduler(engines, queue_size=args.queue_size,
                          admission_timeout_s=args.admission_timeout,
                          batch_wait_ms=args.batch_wait_ms * len(engines),
                          packing=(args.packing == "on"),
                          registry=tel.registry,
                          trace_ring=trace_ring, tracing=tracing,
                          cost_per_device_hour=getattr(
                              args, "cost_per_device_hour", None)).start()

    services = {task: registry.get(task).make_service(
        scheduler, tokenizer, serve_opts) for task in sorted(checkpoints)}

    slo_engine = None
    if getattr(args, "slo_config", None):
        from bert_pytorch_tpu.telemetry.slo import SLOEngine, load_slo_config

        slo_cfg = load_slo_config(args.slo_config)
        slo_engine = SLOEngine(slo_cfg.specs_for("serve"), slo_cfg.windows,
                               tel.registry, phase="serve",
                               trace_ring=scheduler.trace_ring, log=log)
        tel.attach_slo(slo_engine)
        log(f"slo: {len(slo_cfg.specs_for('serve'))} serve spec(s) from "
            f"{args.slo_config} — GET /v1/alerts + /v1/slo; /healthz "
            "status is now the burn-rate engine's verdict")

    # the prober needs the bound port, which only exists once the
    # frontend is up — healthz reads it through this holder instead
    prober_holder = {}

    def healthz():
        h = tel.healthz()
        if prober_holder.get("prober") is not None:
            h["prober"] = prober_holder["prober"].status()
        h.update({
            "tasks": {t: {"checkpoint_step": services_spec[t],
                          "head": registry.get(t).head,
                          "model_params": model_params_count.get(t),
                          "request_schema": dict(
                              registry.get(t).request_schema)}
                      for t in sorted(services_spec)},
            "buckets": list(engine.buckets),
            "packing": args.packing == "on",
            "queue_depth": int(
                scheduler.registry.gauge("bert_serve_queue_depth").value()),
            "serve_dtype": args.serve_dtype,
            "serve_replicas": replicas,
            "serve_mesh": {k: int(v) for k, v in mesh_axes.items()},
            "int8_deltas": {t: {k: round(float(v), 6)
                                for k, v in d.items()}
                            for t, d in sorted(int8_deltas.items())},
            "replicas": scheduler.replica_stats(),
            "request_tracing": (
                dict(scheduler.trace_ring.stats(),
                     cost_per_device_hour=scheduler.cost_per_device_hour)
                if scheduler.trace_ring is not None else None),
        })
        return h

    frontend = ServingFrontend(services, tel.registry, healthz_fn=healthz,
                               port=args.port, host=args.host,
                               trace_ring=scheduler.trace_ring,
                               slo_engine=slo_engine)

    prober = None
    if getattr(args, "prober", "off") == "on":
        from bert_pytorch_tpu.serving.prober import (CanaryProber,
                                                     KNOWN_ANSWER_PAYLOADS)

        probe_tasks = sorted(set(services) & set(KNOWN_ANSWER_PAYLOADS))
        skipped = sorted(set(services) - set(probe_tasks))
        if skipped:
            log(f"prober: no known-answer payload for {skipped}; probing "
                f"{probe_tasks}")
        if probe_tasks:
            prober = CanaryProber(
                frontend.url, probe_tasks,
                interval_s=getattr(args, "probe_interval_s", 5.0),
                timeout_s=getattr(args, "probe_timeout_s", 30.0),
                registry=tel.registry, log=log).start()
            prober_holder["prober"] = prober
            if slo_engine is not None:
                slo_engine.add_alert_source(prober.alerts)
            log(f"prober: canary thread probing "
                f"{{{','.join(probe_tasks)}}} every "
                f"{args.probe_interval_s:g}s through {frontend.url}")

    evaluator = None
    if slo_engine is not None:
        from bert_pytorch_tpu.telemetry.slo import SLOEvaluator

        evaluator = SLOEvaluator(
            slo_engine,
            interval_s=getattr(args, "slo_eval_interval_s", 1.0)).start()

    log(f"serving: listening on {frontend.url} "
        f"(POST /v1/{{{','.join(sorted(services))}}}, GET /metrics, "
        f"GET /healthz"
        + (", GET /v1/traces" if trace_ring is not None else "")
        + (", GET /v1/alerts, GET /v1/slo" if slo_engine is not None
           else "") + ")")
    return ServerHandle(frontend, scheduler, engine, tel, slo=slo_engine,
                        prober=prober, evaluator=evaluator,
                        injector=injector)


def main(argv=None):
    args = parse_arguments(argv)
    if args.force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        # a replica fleet (or mesh slice) needs that many host devices;
        # force them BEFORE jax initializes, same recipe as
        # tests/conftest.py — scripts then just pass --serve_replicas
        need = (max(1, args.serve_replicas)
                * _mesh_slice_size(parse_serve_mesh(args.serve_mesh)))
        flags = os.environ.get("XLA_FLAGS", "")
        if need > 1 and "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={need}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    handle = serve(args)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(handle.port))
        os.replace(tmp, args.port_file)

    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    old = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            old[sig] = signal.signal(sig, _on_signal)
        except ValueError:
            pass  # non-main thread (tests drive serve() directly instead)
    log = handle.tel.logger.info
    try:
        stop.wait()
        # graceful drain (docs/RESILIENCE.md): stop admission first —
        # new requests shed 503 + Retry-After while /metrics + /healthz
        # (now reporting draining:true) keep answering — then let the
        # in-flight requests finish, then tear down and exit 0 so the
        # orchestrator records a clean stop, not a crash
        handle.frontend.begin_drain()
        inflight = handle.frontend.inflight
        log(f"drain: admission stopped (503 + Retry-After); waiting up "
            f"to {args.drain_timeout:g}s for {inflight} in-flight "
            "request(s)")
        drained = handle.frontend.wait_idle(timeout=args.drain_timeout)
        # every replica must come to rest too — a wave sitting on a
        # replica queue when we exit would strand its requests
        drained = (handle.scheduler.wait_idle(timeout=args.drain_timeout)
                   and drained)
        stats = handle.scheduler.replica_stats()
        log(("drain: complete — all in-flight requests finished, "
             if drained else
             f"WARNING: drain deadline ({args.drain_timeout:g}s) hit with "
             f"{handle.frontend.inflight} request(s) still in flight — "
             "closing anyway; ")
            + "replicas "
            + ", ".join(f"r{s['replica']}: {s['dispatched']} waves "
                        f"({s['steals']} stolen)" for s in stats))
    finally:
        for sig, handler in old.items():
            signal.signal(sig, handler)
        # handle.close() flushes metrics sinks via tel.close()
        handle.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
