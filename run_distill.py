#!/usr/bin/env python
"""Distill a teacher checkpoint into a student through the finetune driver.

    python run_distill.py --task classify --student student_6l_768 \
        --teacher_checkpoint teacher_out/ckpt \
        --train_file pairs.tsv --test_file test.tsv \
        --model_config_file teacher_config.json --output_dir student_out \
        --packing --alpha_hidden 1.0

`--task` names any registered task (run_finetune.py's registry);
`--student` a `student_<L>l_<H>` preset (config.student_config) or a
BertConfig JSON path; the rest of the CLI is the task's own parser. The
run rides training/finetune.run_task end to end — packing, telemetry,
preemption guard, watchdog, checkpointing — with the task's loss swapped
for training/distill.py's KD + hard + layer-matched tap mix; the teacher
is restored read-only (serving/engine.restore_serving_params, tolerant
of either encoder layout) and runs under stop_gradient inside the same
jitted step.

Outputs in --output_dir: the student checkpoint (`ckpt/`, serving-
restorable), the student's `model_config.json` (what run_server needs),
and `distill_summary.json` — student/teacher eval accuracy, the
accuracy delta, and the logged train-loss trajectory (first/last KD mix
loss: scripts/check_distill.sh asserts it decreases).

`--inject broken_student` (negative control, CI only): evaluate a
fresh-random student instead of the trained one, so the distillation
accuracy-floor gate (tools/perfboard.py --check_distill) must trip.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def _distill_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--task", default=None,
                   help="registered task to distill (see --list_tasks)")
    p.add_argument("--student", required="--list_tasks" not in sys.argv,
                   help="student preset (student_<L>l_<H>) or a BertConfig "
                        "JSON path")
    p.add_argument("--teacher_checkpoint",
                   required="--list_tasks" not in sys.argv,
                   help="teacher checkpoint dir (or dir@step)")
    p.add_argument("--distill_temperature", type=float, default=2.0)
    p.add_argument("--alpha_kd", type=float, default=1.0,
                   help="soft-target KL weight")
    p.add_argument("--alpha_ce", type=float, default=0.5,
                   help="hard-label task-loss weight")
    p.add_argument("--alpha_hidden", type=float, default=0.0,
                   help="layer-matched mlp_out MSE weight")
    p.add_argument("--alpha_attn", type=float, default=0.0,
                   help="layer-matched attention_out MSE weight")
    p.add_argument("--distill_layer_map", default=None,
                   help="'s:t,s:t,...' student<-teacher layer pairs "
                        "(default: evenly spaced)")
    p.add_argument("--inject", choices=["broken_student"], default=None,
                   help="fault injection for CI negative controls")
    return p


def main(argv=None) -> dict:
    argv = list(sys.argv[1:] if argv is None else argv)

    from bert_pytorch_tpu.tasks import registry

    if "--list_tasks" in argv:
        for name in registry.all_tasks():
            spec = registry.get(name)
            print(f"{name}: {spec.title} [{spec.head}, "
                  f"metric {spec.metric}]")
        return {}

    dargs, rest = _distill_parser().parse_known_args(argv)
    if not dargs.task:
        raise SystemExit(
            "--task <name> is required; registered tasks: "
            + ", ".join(registry.all_tasks()))
    try:
        base_spec = registry.get(dargs.task)
    except KeyError as e:
        raise SystemExit(str(e))
    args = base_spec.parse_arguments(rest)

    # facts produced inside setup (which run_task owns) that the summary
    # written after run_task returns needs
    shared: dict = {}

    def distill_setup(args, config, tel):
        import jax

        from bert_pytorch_tpu.config import BertConfig, student_config
        from bert_pytorch_tpu.serving.engine import restore_serving_params
        from bert_pytorch_tpu.training import distill
        from bert_pytorch_tpu.training.state import unbox

        need_taps = dargs.alpha_hidden > 0 or dargs.alpha_attn > 0
        teacher_cfg = config.replace(
            debug_taps=config.debug_taps or need_taps)
        if dargs.student.endswith(".json"):
            student_cfg = BertConfig.from_json_file(dargs.student).replace(
                vocab_size=teacher_cfg.vocab_size)
        else:
            student_cfg = student_config(dargs.student, teacher_cfg)
        student_cfg = student_cfg.replace(
            debug_taps=student_cfg.debug_taps or need_taps)

        t_run = base_spec.setup(args, teacher_cfg, tel)
        s_run = base_spec.setup(args, student_cfg, tel)

        teacher_params, teacher_step = restore_serving_params(
            dargs.teacher_checkpoint, t_run.model, args.max_seq_len,
            log=tel.logger.info)

        dcfg = distill.DistillConfig(
            temperature=dargs.distill_temperature,
            alpha_kd=dargs.alpha_kd, alpha_ce=dargs.alpha_ce,
            alpha_hidden=dargs.alpha_hidden, alpha_attn=dargs.alpha_attn,
            layer_map=distill.parse_layer_map(
                dargs.distill_layer_map, student_cfg.num_hidden_layers,
                teacher_cfg.num_hidden_layers),
            max_segments=getattr(args, "packing_max_segments", 8))
        tel.logger.info(
            f"distill[{base_spec.name}]: teacher "
            f"{teacher_cfg.num_hidden_layers}L/{teacher_cfg.hidden_size}H "
            f"@{dargs.teacher_checkpoint} step {teacher_step} -> student "
            f"{student_cfg.num_hidden_layers}L/{student_cfg.hidden_size}H "
            f"({dargs.student}), T={dcfg.temperature}, layer map "
            f"{list(dcfg.layer_map)}")

        common = dict(teacher_model=t_run.model,
                      teacher_params=teacher_params, dcfg=dcfg,
                      output_kind=base_spec.output_kind,
                      label_ignore=s_run.label_ignore)
        loss_builder = distill.make_distill_loss_builder(
            packed=False, **common)
        packed_loss_builder = distill.make_distill_loss_builder(
            packed=True, **common)

        base_init = s_run.init_fn
        proj_template = distill.init_projections(
            jax.random.PRNGKey(0), dcfg, student_cfg, teacher_cfg)

        def init_fn(rng):
            variables = base_init(rng)
            if not proj_template:
                return variables
            r_proj = jax.random.fold_in(rng, 0x5D15)
            params = dict(variables["params"])
            params["distill_proj"] = distill.init_projections(
                r_proj, dcfg, student_cfg, teacher_cfg)
            return {**dict(variables), "params": params}

        base_finalize = s_run.finalize

        def finalize(params, results):
            eval_params = params
            if dargs.inject == "broken_student":
                tel.logger.info("distill: INJECTED broken_student — "
                                "evaluating a fresh random student")
                fresh = base_init(jax.random.PRNGKey(args.seed + 1317))
                eval_params = unbox(fresh["params"])
            out = {}
            if base_finalize is not None:
                out.update(base_finalize(eval_params, results) or {})
            if t_run.finalize is not None:
                t_out = t_run.finalize(teacher_params, {}) or {}
                out.update({f"teacher_{k}": v for k, v in t_out.items()})
            if ("test_accuracy" in out
                    and "teacher_test_accuracy" in out):
                out["accuracy_delta"] = (out["teacher_test_accuracy"]
                                         - out["test_accuracy"])
            out["teacher_checkpoint_step"] = teacher_step
            return out

        # the student's serving config — run_server needs the STUDENT
        # depth/width, not the teacher's model_config_file
        cfg_path = os.path.join(args.output_dir, "model_config.json")
        with open(cfg_path, "w", encoding="utf-8") as f:
            f.write(student_cfg.replace(debug_taps=False).to_json_string())
        shared.update(student_config=cfg_path,
                      student_layers=student_cfg.num_hidden_layers,
                      student_hidden=student_cfg.hidden_size,
                      teacher_layers=teacher_cfg.num_hidden_layers,
                      teacher_hidden=teacher_cfg.hidden_size,
                      layer_map=[list(p) for p in dcfg.layer_map],
                      projections=sorted(proj_template))

        return dataclasses.replace(
            s_run, loss_builder=loss_builder,
            packed_loss_builder=packed_loss_builder, init_fn=init_fn,
            finalize=finalize)

    spec = dataclasses.replace(base_spec, setup=distill_setup)

    from bert_pytorch_tpu.training.finetune import run_task

    results = run_task(spec, args)

    # train-loss trajectory from the run's jsonl telemetry sink: the
    # check_distill.sh KD-loss-decrease assertion reads first vs last
    log_prefix = getattr(args, "log_prefix", None) or f"{spec.name}_log"
    jsonl = os.path.join(args.output_dir, f"{log_prefix}.jsonl")
    train_losses = []
    try:
        with open(jsonl, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("tag") == "train" and "loss" in rec:
                    train_losses.append(float(rec["loss"]))
    except OSError:
        pass

    summary = {
        "kind": "distill_run",
        "task": dargs.task,
        "student": dargs.student,
        "teacher_checkpoint": dargs.teacher_checkpoint,
        "temperature": dargs.distill_temperature,
        "alpha_kd": dargs.alpha_kd, "alpha_ce": dargs.alpha_ce,
        "alpha_hidden": dargs.alpha_hidden,
        "alpha_attn": dargs.alpha_attn,
        "inject": dargs.inject,
        "train_losses": train_losses,
        "loss_first": train_losses[0] if train_losses else None,
        "loss_last": train_losses[-1] if train_losses else None,
        **shared,
        **{k: v for k, v in results.items()
           if isinstance(v, (int, float, str))},
    }
    out_path = os.path.join(args.output_dir, "distill_summary.json")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"distill: summary -> {out_path}")
    return summary


if __name__ == "__main__":
    main()
