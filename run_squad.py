#!/usr/bin/env python
"""SQuAD v1.1/v2.0 finetune + predict + eval entry point, TPU-native.

Parity with the reference run_squad.py (CLI :729-859, train :1067-1117,
predict :1130-1178, eval :1197-1224) minus the CUDA-era machinery: no apex
AMP/GradScaler (bf16), no DDP wrapper (jit over the mesh), no eval
subprocess (in-process v1.1 metric, tasks/squad.py).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config_file", default=None, type=str)
    p.add_argument("--bert_model", default="bert-large-uncased", type=str)
    p.add_argument("--output_dir", required=False, default=None, type=str)
    p.add_argument("--train_file", default=None, type=str)
    p.add_argument("--predict_file", default=None, type=str)
    p.add_argument("--init_checkpoint", default=None, type=str,
                   help="pretraining checkpoint dir (orbax) or none")
    p.add_argument("--model_config_file", default=None, type=str)
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--do_train", action="store_true")
    p.add_argument("--do_predict", action="store_true")
    p.add_argument("--do_eval", action="store_true")
    p.add_argument("--do_lower_case", action="store_true", default=True)
    p.add_argument("--max_seq_length", default=384, type=int)
    p.add_argument("--doc_stride", default=128, type=int)
    p.add_argument("--max_query_length", default=64, type=int)
    p.add_argument("--train_batch_size", default=32, type=int)
    p.add_argument("--predict_batch_size", default=8, type=int)
    p.add_argument("--learning_rate", default=3e-5, type=float,
                   help="peak LR. The finetune optimizer keeps apex "
                        "FusedAdam's bias_correction=False semantics "
                        "(reference run_squad.py:982-988), which amplifies "
                        "early updates ~(1/sqrt(1-b2))x; measured on v5e, "
                        "3e-4 diverges the encoder to chance while 5e-5 "
                        "reaches 100 F1 on an overfit probe — stay near the "
                        "reference's 3e-5 scale")
    p.add_argument("--num_train_epochs", default=2.0, type=float)
    p.add_argument("--max_steps", default=-1.0, type=float,
                   help="early exit for benchmarking (reference :1070-1073)")
    p.add_argument("--warmup_proportion", default=0.1, type=float)
    p.add_argument("--n_best_size", default=20, type=int)
    p.add_argument("--max_answer_length", default=30, type=int)
    p.add_argument("--verbose_logging", action="store_true")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--gradient_accumulation_steps", type=int, default=1)
    p.add_argument("--version_2_with_negative", action="store_true")
    p.add_argument("--null_score_diff_threshold", type=float, default=0.0)
    p.add_argument("--max_grad_norm", type=float, default=1.0)
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--log_prefix", type=str, default="squad_log")
    p.add_argument("--watchdog_timeout", type=float, default=0.0,
                   help="hung-step watchdog (resilience/watchdog.py): a "
                        "host phase exceeding this many seconds dumps "
                        "all-thread stacks and acts per "
                        "--watchdog_action; 0 = off (docs/RESILIENCE.md)")
    p.add_argument("--watchdog_action", type=str, default="abort",
                   choices=["abort", "warn"])
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve live /metrics + /healthz on this port while "
                        "the run is alive (telemetry/exporter.py; 0 = "
                        "ephemeral). Default: off")
    p.add_argument("--eval_script", default=None, type=str,
                   help="unused (in-process eval); kept for CLI parity")

    from bert_pytorch_tpu.config import merge_args_with_config

    return merge_args_with_config(p, argv)


def _is_tf_source(path: str) -> bool:
    """Does `path` name an external weight source — a Google TF release
    (registry name, URL, zip, extracted dir, bare ckpt prefix) or a
    reference torch checkpoint (ckpt_*.pt) — rather than one of this
    framework's orbax checkpoints?"""
    from bert_pytorch_tpu.models.pretrained import PRETRAINED_ARCHIVE_MAP

    if path in PRETRAINED_ARCHIVE_MAP or "://" in path \
            or path.endswith((".zip", ".ckpt", ".pt", ".pth", ".bin")):
        return True
    if os.path.isdir(path):
        for _root, _dirs, files in os.walk(path):
            if "bert_config.json" in files \
                    or any(f.endswith(".ckpt.index") for f in files):
                return True
        return False
    return os.path.exists(path + ".index")


def load_pretrained_params(init_checkpoint: str, current_params,
                           log=None):
    """Load encoder weights from a pretraining checkpoint — this framework's
    orbax checkpoints, a Google TF BERT release (zip / URL / extracted dir /
    registry name), or a reference torch save — returning the FINAL param
    tree: loaded leaves replace current ones (placed with their
    dtype/sharding), everything else keeps its current init. Tolerant of
    missing/extra heads
    (reference loads ckpt['model'] with strict=False, run_squad.py:961; TF
    import parity: src/modeling.py:58-116).

    Every subtree that does NOT come from the checkpoint is reported loudly:
    a wrong --init_checkpoint must not silently train from scratch. Raises if
    nothing at all matches (that checkpoint is certainly not a BERT encoder
    for this config)."""
    import jax

    if _is_tf_source(init_checkpoint):
        from bert_pytorch_tpu.models.pretrained import from_pretrained

        vocab = int(np.shape(jax.tree.leaves(
            current_params["bert"]["embeddings"]["word_embeddings"])[0])[0])
        _, src = from_pretrained(init_checkpoint, next_sentence=True,
                                 vocab_pad_multiple=1)
        # re-pad the release vocab to this model's padded size
        emb = src["bert"]["embeddings"]["word_embeddings"]["embedding"]
        if emb.shape[0] < vocab:
            from bert_pytorch_tpu.models.pretrained import (
                PADDED_VOCAB_BIAS, _pad_vocab)

            src["bert"]["embeddings"]["word_embeddings"]["embedding"] = \
                _pad_vocab(emb, vocab, 0.0)
            src["cls_predictions"]["bias"] = _pad_vocab(
                src["cls_predictions"]["bias"], vocab, PADDED_VOCAB_BIAS)
        step = ("torch-ckpt" if init_checkpoint.endswith(
            (".pt", ".pth", ".bin")) else "tf-release")
    else:
        from bert_pytorch_tpu.training.checkpoint import CheckpointManager

        # 'dir@step' selects a specific checkpoint step (finetune curves
        # against intermediate pretraining checkpoints); bare dir = latest
        want_step = None
        ckpt_dir = init_checkpoint
        if "@" in init_checkpoint:
            head, _, tail = init_checkpoint.rpartition("@")
            if tail.isdigit():
                ckpt_dir, want_step = head, int(tail)
        mgr = CheckpointManager(ckpt_dir)
        state, step = mgr.restore_raw(step=want_step)
        mgr.close()
        src = state["params"]

    # align the source's encoder layer layout (scan-stacked vs per-layer)
    # with the target model's before the path-wise merge — a stacked-era
    # checkpoint must seed an unstacked model and vice versa
    from bert_pytorch_tpu.models.pretrained import (convert_tree_layout,
                                                    tree_layout)

    want_layout = tree_layout(current_params)
    if want_layout is not None and tree_layout(src) not in (None, want_layout):
        src = convert_tree_layout(src, stacked=(want_layout == "stacked"))

    loaded, fresh = [], []

    def merge(dst, src_tree, path=()):
        out = {}
        for k, v in dst.items():
            child_path = path + (k,)
            if isinstance(v, dict):
                out[k] = merge(v, src_tree.get(k, {}) if isinstance(
                    src_tree, dict) else {}, child_path)
            else:
                cand = src_tree.get(k) if isinstance(src_tree, dict) else None
                name = "/".join(child_path)
                if cand is not None and tuple(np.shape(cand)) == tuple(v.shape):
                    out[k] = jax.numpy.asarray(cand, v.dtype)
                    loaded.append(name)
                else:
                    out[k] = None  # keep fresh init
                    fresh.append(name + ("" if cand is None
                                         else f" (shape {np.shape(cand)} != "
                                              f"{tuple(v.shape)})"))
        return out

    merged = merge(current_params, src)
    emit = log if log is not None else print
    emit(f"init_checkpoint step {step}: loaded {len(loaded)} param leaves, "
         f"{len(fresh)} fresh-initialized")
    if fresh:
        emit("WARNING: fresh-initialized (not found in checkpoint or shape "
             "mismatch): " + ", ".join(sorted(fresh)))
    if not loaded:
        raise ValueError(
            f"checkpoint {init_checkpoint} (step {step}) shares no "
            "same-shaped parameters with this model — wrong checkpoint?")

    # apply the merge here so every caller gets final params: a loaded leaf
    # is placed with the current leaf's dtype/sharding, a fresh leaf IS the
    # current (initialized) leaf object
    def take(cur, new):
        if new is None:
            return cur
        if isinstance(cur, jax.Array) and hasattr(cur, "sharding"):
            return jax.device_put(new, cur.sharding)
        return new

    return jax.tree.map(take, current_params, merged)


def main(argv=None):
    args = parse_arguments(argv)
    if not args.output_dir:
        raise SystemExit("--output_dir is required")
    os.makedirs(args.output_dir, exist_ok=True)

    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.models import BertForQuestionAnswering, losses
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.adam import fused_adam
    from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask
    from bert_pytorch_tpu.parallel import dist
    from bert_pytorch_tpu.tasks import squad
    from bert_pytorch_tpu.telemetry import (collect_provenance,
                                            flops_per_seq, init_run,
                                            lookup_peak_flops)
    from bert_pytorch_tpu.telemetry.stepwatch import DEFAULT_PEAK
    from bert_pytorch_tpu.training import TrainState, make_sharded_state

    np.random.seed(args.seed)
    # the single telemetry wiring path (telemetry/run.py) — same call as
    # run_pretraining/run_ner/bench, so every phase's records share one
    # schema and the /metrics endpoint comes for free
    tel = init_run(
        phase="squad",
        log_prefix=os.path.join(args.output_dir, args.log_prefix),
        verbose=dist.is_main_process(), jsonl=True,
        metrics_port=args.metrics_port)
    logger = tel.logger
    compile_watch = tel.compile_watch
    # survival kit (docs/RESILIENCE.md): SIGTERM/SIGINT -> emergency
    # checkpoint of the in-progress finetune state; optional hung-step
    # watchdog
    from bert_pytorch_tpu.resilience import PreemptionGuard
    from bert_pytorch_tpu.resilience.preemption import \
        finetune_emergency_save
    from bert_pytorch_tpu.resilience.watchdog import arm_watchdog

    guard = PreemptionGuard(registry=tel.registry, log=logger.info)
    guard.install()
    watchdog = None
    survival = {}  # latest (state, step) the except-path may checkpoint
    try:
        tel.log_header(**collect_provenance())

        config = BertConfig.from_json_file(args.model_config_file)
        vocab_file = args.vocab_file or config.vocab_file
        config = config.replace(
            vocab_size=pad_vocab_size(config.vocab_size, 8))
        compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                         else jnp.float32)
        model = BertForQuestionAnswering(config, dtype=compute_dtype)
        tokenizer = get_wordpiece_tokenizer(vocab_file,
                                            uppercase=not config.lowercase)

        sample_ids = jnp.zeros((2, args.max_seq_length), jnp.int32)
        init_fn = lambda r: model.init(r, sample_ids, sample_ids, sample_ids)

        results = {}

        # ---------------- train -------------------------------------------
        if args.do_train:
            examples = squad.read_squad_examples(
                args.train_file, is_training=True,
                version_2_with_negative=args.version_2_with_negative)
            cache = os.path.join(
                args.output_dir,
                f"train_feats_{args.max_seq_length}_{args.doc_stride}.pkl")
            feats = squad.cached_features(cache, lambda: (
                squad.convert_examples_to_features(
                    examples, tokenizer, args.max_seq_length,
                    args.doc_stride, args.max_query_length,
                    is_training=True)))
            arrays = squad.features_to_arrays(feats, is_training=True)
            # optimizer steps per epoch: each step consumes batch*accum
            # examples (reference divides num_train_optimization_steps the
            # same way, run_squad.py:966-970)
            examples_per_step = (args.train_batch_size
                                 * args.gradient_accumulation_steps)
            steps_per_epoch = len(feats) // examples_per_step
            total_steps = int(steps_per_epoch * args.num_train_epochs)
            if args.max_steps > 0:
                total_steps = min(total_steps, int(args.max_steps))

            sched = schedulers.linear_warmup_schedule(
                args.learning_rate, total_steps,
                warmup=args.warmup_proportion)
            import optax

            # two param groups: wd 0.01 everywhere except bias/LayerNorm
            # (reference run_squad.py:974-986)
            tx = fused_adam(sched, weight_decay=0.01,
                            weight_decay_mask=default_weight_decay_mask,
                            bias_correction=False)
            if args.max_grad_norm and args.max_grad_norm > 0:
                # reference GradientClipper global-norm clip before the step
                # (run_squad.py:703-725,1104)
                tx = optax.chain(
                    optax.clip_by_global_norm(args.max_grad_norm), tx)

            def loss_builder(model):
                def loss_fn(params, batch, rng, deterministic=False):
                    start, end = model.apply(
                        {"params": params}, batch["input_ids"],
                        batch["token_type_ids"], batch["attention_mask"],
                        deterministic=deterministic,
                        rngs=None if deterministic else {"dropout": rng})
                    loss = losses.qa_loss(start, end,
                                          batch["start_positions"],
                                          batch["end_positions"])
                    return loss, {}
                return loss_fn

            from bert_pytorch_tpu.training.pretrain import \
                build_pretrain_step

            step_fn = build_pretrain_step(
                model, tx, schedule=sched,
                accum_steps=args.gradient_accumulation_steps,
                loss_fn_builder=loss_builder)
            state, _ = make_sharded_state(jax.random.PRNGKey(args.seed),
                                          init_fn, tx)
            if args.init_checkpoint:
                params = load_pretrained_params(args.init_checkpoint,
                                                state.params,
                                                log=logger.info)
                state = TrainState(step=state.step, params=params,
                                   opt_state=state.opt_state)
                logger.info(f"loaded pretrained weights from "
                            f"{args.init_checkpoint}")

            jit_step = jax.jit(step_fn, donate_argnums=(0,))

            # real StepWatch perf records (same shared flops_per_seq the
            # pretrainer and bench use): finetuning has no gathered MLM
            # head, so n_pred=0 — the (E, 2) QA head is noise next to the
            # trunk. seqs_per_step = one optimization step's examples.
            seqs_per_step = (args.train_batch_size
                             * args.gradient_accumulation_steps)
            peak = lookup_peak_flops(jax.devices()[0].device_kind)
            sw = tel.make_stepwatch(
                flops_per_step=flops_per_seq(
                    config, args.max_seq_length, config.vocab_size, 0)
                * seqs_per_step,
                seqs_per_step=seqs_per_step,
                seq_len=args.max_seq_length,
                peak_flops=(peak or DEFAULT_PEAK) * jax.device_count(),
                log_freq=50)
            watchdog = arm_watchdog(
                args.watchdog_timeout, args.watchdog_action, sw,
                registry=tel.registry, log=logger.info,
                out_dir=args.output_dir)

            rng = jax.random.PRNGKey(args.seed)
            t0 = time.time()
            step = 0
            done = False
            epoch = 0
            while not done:
                for batch_np, _real in squad.batches(
                        arrays,
                        args.train_batch_size
                        * args.gradient_accumulation_steps,
                        shuffle=True, seed=args.seed + epoch):
                    if step >= total_steps:
                        done = True
                        break
                    with sw.phase("data_prep"):
                        stacked = {
                            k: v.reshape(args.gradient_accumulation_steps,
                                         args.train_batch_size,
                                         *v.shape[1:])
                            for k, v in batch_np.items()
                            if k != "unique_ids"}
                        batch = {k: jnp.asarray(v)
                                 for k, v in stacked.items()}
                    rng, srng = jax.random.split(rng)
                    with sw.phase("dispatch"):
                        state, metrics = jit_step(state, batch, srng)
                    step += 1
                    survival["state"], survival["step"] = state, step
                    if step % 50 == 0 or step == total_steps:
                        with sw.phase("metric_flush"):
                            tel.log_train(step,
                                          loss=float(metrics["loss"]),
                                          learning_rate=float(
                                              metrics["learning_rate"]))
                    perf = sw.step_done()
                    if perf is not None:
                        tel.log_perf(step, perf)
                epoch += 1
            perf = sw.flush()  # partial interval: short runs still get one
            if perf is not None:
                tel.log_perf(step, perf)
            train_time = time.time() - t0
            results["e2e_train_time"] = train_time
            results["training_sequences_per_second"] = (
                args.train_batch_size * args.gradient_accumulation_steps
                * step / max(train_time, 1e-9))

            # save finetuned checkpoint (reference :1121-1128)
            from bert_pytorch_tpu.training.checkpoint import \
                CheckpointManager

            mgr = CheckpointManager(os.path.join(args.output_dir, "ckpt"))
            mgr.save(step, state, extra={"task": "squad",
                                         "config": config.to_dict()})
            mgr.close()
            final_params = state.params
        else:
            state, _ = make_sharded_state(
                jax.random.PRNGKey(args.seed), init_fn,
                fused_adam(1e-5))
            if args.init_checkpoint:
                final_params = load_pretrained_params(
                    args.init_checkpoint, state.params, log=logger.info)
            else:
                final_params = state.params

        # ---------------- predict -----------------------------------------
        if args.do_predict:
            eval_examples = squad.read_squad_examples(
                args.predict_file, is_training=False,
                version_2_with_negative=args.version_2_with_negative)
            eval_feats = squad.convert_examples_to_features(
                eval_examples, tokenizer, args.max_seq_length,
                args.doc_stride, args.max_query_length, is_training=False)
            eval_arrays = squad.features_to_arrays(eval_feats,
                                                   is_training=False)

            # the SAME pure forward + RawResult assembly the serving
            # engine compiles (tasks/predict.py) — eval and serving can
            # no longer fork the logits path
            from bert_pytorch_tpu.tasks import predict

            predict_step = jax.jit(predict.build_qa_forward(model))

            raw_results = []
            t0 = time.time()
            for batch_np, real in squad.batches(eval_arrays,
                                                args.predict_batch_size):
                uids = batch_np.pop("unique_ids")
                batch = {k: jnp.asarray(v) for k, v in batch_np.items()}
                start, end = predict_step(final_params, batch)
                raw_results.extend(
                    predict.qa_raw_results(uids, start, end, real))
            infer_time = time.time() - t0
            results["e2e_inference_time"] = infer_time
            results["inference_sequences_per_second"] = (
                len(eval_feats) / max(infer_time, 1e-9))

            answers, nbest = squad.get_answers(
                eval_examples, eval_feats, raw_results,
                squad.AnswerConfig(
                    n_best_size=args.n_best_size,
                    max_answer_length=args.max_answer_length,
                    do_lower_case=config.lowercase,
                    version_2_with_negative=args.version_2_with_negative,
                    null_score_diff_threshold=args.null_score_diff_threshold,
                    verbose_logging=args.verbose_logging))
            pred_file = os.path.join(args.output_dir, "predictions.json")
            with open(pred_file, "w", encoding="utf-8") as f:
                json.dump(answers, f, indent=2)
            with open(os.path.join(args.output_dir,
                                   "nbest_predictions.json"),
                      "w", encoding="utf-8") as f:
                json.dump(nbest, f, indent=2)

            if args.do_eval:
                # v1.1 runs the official evaluate-v1.1 math; v2 needs the
                # no-answer-aware metric (the reference's --do_eval only ever
                # shells out to the v1.1 script, run_squad.py:1197-1204)
                eval_fn = (squad.evaluate_v2 if args.version_2_with_negative
                           else squad.evaluate_v1)
                metrics = eval_fn(args.predict_file, answers)
                results.update(metrics)

        # final structured records (reference run_squad.py:1211-1224 logged
        # e2e_train_time / training_sequences_per_second /
        # e2e_inference_time / inference_sequences_per_second / exact_match /
        # F1 via dllogger)
        if results:
            logger.log("final", 0, **results)
        logger.info(json.dumps(results))
        logger.info(f"compiles: {compile_watch.snapshot()}")
        return results
    except BaseException as exc:
        # preemption-safe finetuning: SIGTERM/SIGINT mid-epoch saves the
        # in-progress state (the reference lost the whole finetune run)
        finetune_emergency_save(guard, exc, survival,
                                os.path.join(args.output_dir, "ckpt"),
                                "squad", registry=tel.registry,
                                log=logger.info)
        raise
    finally:
        for closeable in (watchdog, guard):
            if closeable is not None:
                try:
                    closeable.close()
                except Exception:
                    pass
        tel.close()


if __name__ == "__main__":
    main()
