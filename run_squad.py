#!/usr/bin/env python
"""SQuAD v1.1/v2.0 finetune + predict + eval entry point, TPU-native.

Thin alias of `run_finetune.py --task squad` (identical CLI — parity
with the reference run_squad.py CLI :729-859): the task-shaped half
lives in bert_pytorch_tpu/tasks/squad_task.py, the shared loop in
bert_pytorch_tpu/training/finetune.py. `load_pretrained_params` is
re-exported here for backward compatibility (it moved to the shared
driver so every registered task seeds checkpoints the same way).
"""

from __future__ import annotations

# compat re-export: tests and downstream scripts import it from here
from bert_pytorch_tpu.training.finetune import (  # noqa: F401
    load_pretrained_params)


def parse_arguments(argv=None):
    from bert_pytorch_tpu.tasks.squad_task import parse_arguments as parse

    return parse(argv)


def main(argv=None):
    from bert_pytorch_tpu.tasks import registry
    from bert_pytorch_tpu.training.finetune import run_task

    return run_task(registry.get("squad"), parse_arguments(argv))


if __name__ == "__main__":
    main()
