#!/usr/bin/env python
"""Collective/compute/host time attribution from a jax.profiler trace.

The multichip bench measures WHAT a variant costs; this tool says WHERE the
time goes. Point it at a profiler log dir (the `--profile_steps` output of
run_pretraining, a `BENCH_PROFILE_DIR`, or the per-variant trace dirs
bench.py --multichip writes) and it buckets every op event into

  collective  — all-gather / all-reduce / reduce-scatter / collective-permute
                / all-to-all (async -start/-done and fusions included),
  compute     — every other HLO op,
  host        — the train loop's TraceAnnotations (host/data_wait, host/h2d,
                host/dispatch, host/metric_flush, ...), per phase,

with same-bucket overlaps interval-merged per thread so nothing is counted
twice (telemetry/trace.py is the engine; stdlib-only, runs anywhere).

  python tools/trace_summary.py --trace results/phase1/traces
  python tools/trace_summary.py --trace traces/ --steps 10 --devices 8
  python tools/trace_summary.py --trace traces/ --json out.json

--steps / --devices add per-step / per-device normalizations (a
single-process n-device mesh logs every device's ops into one trace, so raw
bucket totals are device-seconds). Exit 0 with a table on stdout; --json
additionally writes the machine-readable summary (the same dict bench.py
embeds in MULTICHIP_r*.json per variant).

--requests switches to SERVING request-trace mode: point --trace at a
/v1/traces export (what `tools/loadtest.py --save_traces` writes) and the
table becomes per-phase p50/p99 latency attribution across request
timelines — admit/queue_wait/pack/dispatch/compute/demux/respond — ending
with the tail headline: which phase dominates the p99 cohort and on which
replica ("p99 is 78% queue_wait on r0").

  python tools/trace_summary.py --requests --trace traces_r1_f32.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.telemetry.trace import (  # noqa: E402
    find_trace_file, load_trace_events, summarize_request_events,
    summarize_trace)


def format_summary(s: dict) -> str:
    lines = [f"trace: {s.get('trace_file', '?')}",
             f"events classified: {s['events_classified']}"]
    if s.get("truncated"):
        lines.append(
            f"WARNING: {s['truncated_intervals']} interval(s) never "
            "completed (trace cut short mid-op — crashed run?); closed at "
            "the trace end and included in the totals")
    dev = f" ({s['n_devices']} devices)" if "n_devices" in s else ""
    lines.append(
        f"collective: {s['collective_ms']:.1f} ms"
        f"  compute: {s['compute_ms']:.1f} ms"
        f"  collective_fraction: {s['collective_fraction']:.1%}{dev}")
    if "collective_ms_per_step_device" in s:
        basis = ("per step per device" if "n_devices" in s
                 else "per step (device-seconds; pass --devices to "
                      "normalize)")
        lines.append(
            f"{basis}: collective "
            f"{s['collective_ms_per_step_device']:.2f} ms, compute "
            f"{s['compute_ms_per_step_device']:.2f} ms "
            f"({s['steps']} steps)")
    if s.get("collective_kind_ms"):
        total = max(s["collective_ms"], 1e-9)
        lines.append("collectives by kind (device-ms; class-merged, "
                     "overlap means kinds need not sum to the total):")
        for kind, ms in sorted(s["collective_kind_ms"].items(),
                               key=lambda kv: -kv[1]):
            lines.append(f"  {kind:<24} {ms:>10.1f} ms "
                         f"({ms / total:6.1%} of collective)")
    if s["collective_by_op_ms"]:
        lines.append("collectives by op:")
        for op, ms in sorted(s["collective_by_op_ms"].items(),
                             key=lambda kv: -kv[1]):
            lines.append(f"  {op:<24} {ms:>10.1f} ms")
    if s["host_ms"]:
        lines.append("host phases:")
        for phase, ms in sorted(s["host_ms"].items(),
                                key=lambda kv: -kv[1]):
            lines.append(f"  {phase:<24} {ms:>10.1f} ms")
    return "\n".join(lines)


def format_request_summary(s: dict) -> str:
    lines = [f"request traces: {s['n_traces']}"]
    if not s["n_traces"]:
        lines.append("(no req/ spans in this trace — is it a /v1/traces "
                     "export?)")
        return "\n".join(lines)
    lines.append("  by outcome: " + ", ".join(
        f"{k}={v}" for k, v in sorted(s["by_outcome"].items())))
    lines.append("  by task:    " + ", ".join(
        f"{k}={v}" for k, v in sorted(s["by_task"].items())))
    lines.append(f"{'phase':<12} {'count':>6} {'p50 ms':>10} "
                 f"{'p99 ms':>10} {'mean ms':>10}")
    for phase, st in s["phases"].items():
        lines.append(f"{phase:<12} {st['count']:>6} {st['p50_ms']:>10.2f} "
                     f"{st['p99_ms']:>10.2f} {st['mean_ms']:>10.2f}")
    tot = s["total_ms"]
    lines.append(f"{'total':<12} {s['n_traces']:>6} {tot['p50']:>10.2f} "
                 f"{tot['p99']:>10.2f} {tot['mean']:>10.2f}")
    p99 = s.get("p99") or {}
    if p99.get("dominant_phase"):
        where = f" on {p99['replica']}" if p99.get("replica") else ""
        lines.append(
            f"p99 is {p99['dominant_share']:.0%} "
            f"{p99['dominant_phase']}{where} "
            f"({p99['n_traces']} trace(s) at/above "
            f"{p99['total_ms']:.1f} ms)")
    return "\n".join(lines)


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", required=True,
                    help="profiler log dir (or a *.trace.json.gz directly)")
    ap.add_argument("--requests", action="store_true",
                    help="summarize serving request spans (a /v1/traces "
                         "export) instead of device op time")
    ap.add_argument("--ids", default=None,
                    help="--requests: only summarize these comma-separated "
                         "trace ids — paste the trace_ids a firing "
                         "latency alert carries (GET /v1/alerts, "
                         "docs/OBSERVABILITY.md) to attribute exactly the "
                         "requests that burned the budget")
    ap.add_argument("--steps", type=int, default=None,
                    help="optimization steps the traced window covered")
    ap.add_argument("--devices", type=int, default=None,
                    help="devices sharing this trace (single-process mesh)")
    ap.add_argument("--json", default=None,
                    help="also write the summary dict to this path")
    args = ap.parse_args(argv)

    if args.requests:
        trace_file = find_trace_file(args.trace)
        events = load_trace_events(trace_file)
        if args.ids:
            want = {i.strip() for i in args.ids.split(",") if i.strip()}
            events = [e for e in events
                      if (e.get("args") or {}).get("trace_id") in want]
            if not events:
                print(f"trace_summary: none of the {len(want)} requested "
                      f"id(s) appear in {trace_file} (the ring only "
                      "retains the slowest + sampled traces; export soon "
                      "after the alert fires)", file=sys.stderr)
        summary = summarize_request_events(events)
        summary["trace_file"] = trace_file
        if args.ids:
            summary["filtered_ids"] = sorted(
                i.strip() for i in args.ids.split(",") if i.strip())
        print(format_request_summary(summary))
    else:
        summary = summarize_trace(args.trace, steps=args.steps,
                                  n_devices=args.devices)
        print(format_summary(summary))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
        print(f"wrote {args.json}")
    return summary


if __name__ == "__main__":
    main()
