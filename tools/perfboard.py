#!/usr/bin/env python
"""Cross-run perf board: index BENCH/MULTICHIP/run-log artifacts, gate regressions.

Seven rounds of perf artifacts (BENCH_r01-r05, MULTICHIP_r01-r07) were
write-only: every number was recorded, none was ever compared, so a
regression had to be noticed by a human re-reading JSON. This tool closes
that loop, jax-free (it must run on a login host, in CI, and in the
deliberately backend-free bench parent):

  python tools/perfboard.py
      # index: scan <root> for BENCH_*.json / MULTICHIP_*.json /
      # SERVE_*.json / DISTILL_*.json / FINETUNE_*.json
      # (+ results/graph_report.json), write
      # results/runs.jsonl (one record per artifact) and RUNS.md (the
      # human trend table). Deterministic: same artifacts -> same bytes.

  python tools/perfboard.py --runs 'results/phase1/*.jsonl'
      # additionally index MetricLogger run logs (tag 'perf' records ->
      # per-run medians of step time / seq/s / MFU / packing efficiency)

  python tools/perfboard.py --check BASELINE.json CURRENT.json --tolerance 0.1
      # regression gate: extract the same metrics from both artifacts and
      # exit 1 naming every gated metric that moved the WRONG way by more
      # than the tolerance. Exit 0 inside tolerance, 2 on unusable input.
      # scripts/check_perf.sh runs this over the newest two MULTICHIP
      # artifacts.

  python tools/perfboard.py --check_distill DISTILL_r01.json \
      --distill_max_delta 0.05
      # distillation accuracy floor: every student serving leg in the
      # artifact must be within the floor of its teacher's accuracy
      # (direction-aware: students that beat the teacher always pass).

Gating rules: throughput/efficiency metrics (seq/s, MFU, scaling
efficiency, vs_baseline, packing speedup, serving req/s + real tokens/s
+ batch occupancy) are higher-better; serving latency percentiles
(p50/p95/p99) and step-time RATIOS (zero1 vs dp etc.) are lower-better. Absolute `*_ms` step times
are indexed for the trend table but NOT gated — they are the reciprocal
view of seq/s, and double-gating the same quantity just doubles the
false-alarm rate. A metric present in the baseline but missing from the
current artifact is reported loudly but does not fail the gate (artifact
shapes evolve); a metric moving the RIGHT way never fails regardless of
size.

Cost/throughput accounting as a first-class per-run artifact follows
PAPERS.md "Multi-node BERT-pretraining: Cost-efficient Approach"
(2008.00177); docs/OBSERVABILITY.md has the operator guide.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# script mode (`python tools/perfboard.py`) puts tools/ first on sys.path;
# the graph-report metrics borrow the estimate formula from
# bert_pytorch_tpu.analysis (stdlib-only import, still jax-free)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_TOLERANCE = 0.1

# metric-name -> gating direction. Ordered: first match wins. Step-time
# RATIOS (zero1 vs dp etc.) are index-only like absolute step times: both
# operands are independently gated throughput metrics, so gating the
# derived ratio only double-counts the same movement. Absolute step times
# ('step_time_ms', 'step_time_ms_median') are the reciprocal view of
# seq/s — also index-only. Run-length bookkeeping (last_step,
# perf_intervals) describes how long a run was, not how fast.
# Serving latency percentiles (p50/p95/p99_ms) ARE gated lower-better
# despite the _ms suffix: unlike a train step's time they are NOT the
# reciprocal of a gated throughput — an overloaded server can hold req/s
# while its tail latency explodes, which is exactly the regression class
# the SERVE gate exists for.
_LOWER_BETTER_MARKERS = ("pad_fraction", "data_wait",
                         # graph-report metrics: collectives and the
                         # static memory estimate regress UPWARD
                         ".collectives.", "est_device_mb",
                         "donated_unaliased",
                         # traced collective-time fraction per multichip
                         # variant (round 15): the share of device time in
                         # collectives is the scaling ceiling under
                         # attack — a ratio, so robust to the CPU
                         # harness's wall-clock noise, gated lower-better
                         "collective_fraction",
                         # serving latency percentiles (SERVE_*.json)
                         "p50_ms", "p95_ms", "p99_ms",
                         # serving cost accounting (round 18): device-
                         # seconds spent per 1k real tokens x the device-
                         # hour price — the dollar regression class
                         # (occupancy collapse, replica idling) that req/s
                         # alone cannot see
                         "cost_per_1k_tokens",
                         # distillation (round 19): accuracy_delta is
                         # teacher minus student accuracy — it growing
                         # means the student got WORSE relative to its
                         # teacher, so the gate direction is lower-better
                         # (a student beating its teacher, delta < 0,
                         # never regresses). Plain per-leg `accuracy`
                         # stays higher-better by default.
                         "accuracy_delta")
# p99 tail attribution (request traces): WHERE the tail goes is a
# diagnostic split of an already-gated p99, so the per-phase ms and the
# dominant share are indexed for the trend table but never gated
_UNGATED_MARKERS = ("step_time_ratio", "step_time_ms", "p99_attribution")
_UNGATED_SUFFIXES = ("_ms",)
_UNGATED_NAMES = frozenset({"last_step", "perf_intervals"})


def metric_direction(name: str) -> Optional[str]:
    """'higher' | 'lower' | 'nonzero' | None (indexed but not gated)."""
    if name.endswith(".collectives.reduce-scatter"):
        # round 16: reduce-scatter is the DESIRED collective on the ZeRO-1
        # rs path (half the bytes of all-reduce-then-slice), so unlike the
        # other .collectives. counts its appearance is progress, not
        # regression. The failure mode worth gating is the opposite edge:
        # a combo that had reduce-scatters compiling to zero again means
        # the rs path silently fell back to all-reduce — 'nonzero' gates
        # exactly baseline>0 -> current==0.
        return "nonzero"
    if any(m in name for m in _LOWER_BETTER_MARKERS):
        return "lower"
    if name in _UNGATED_NAMES \
            or any(m in name for m in _UNGATED_MARKERS) \
            or name.endswith(_UNGATED_SUFFIXES):
        return None
    return "higher"


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _round_of(path: str) -> Optional[int]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


# -- extractors ---------------------------------------------------------------


def detect_kind(data: Any, path: str = "") -> Optional[str]:
    base = os.path.basename(path)
    if isinstance(data, dict):
        if "variants" in data or base.startswith("MULTICHIP"):
            return "multichip"
        if "parsed" in data or base.startswith("BENCH"):
            return "bench"
        if "combos" in data or base.startswith("graph_report"):
            return "graph"
        if data.get("kind") == "distill" or base.startswith("DISTILL"):
            return "distill"
        if "modes" in data or base.startswith("SERVE"):
            return "serve"
        if data.get("kind") == "finetune" or base.startswith("FINETUNE"):
            return "finetune"
    return None


def finetune_metrics(data: Dict[str, Any]) -> Dict[str, float]:
    """Flat comparable metrics from a FINETUNE_*.json artifact
    (training/finetune.write_finetune_artifact, one record per task):
    real tokens/s, seq/s and MFU gate higher-better; pad_fraction
    lower-better (the `pad_fraction` marker); absolute step_time_ms
    stays index-only like every other train-step time."""
    out: Dict[str, float] = {}
    for task, rec in sorted((data.get("tasks") or {}).items()):
        if not isinstance(rec, dict):
            continue
        for k in ("real_tokens_per_sec", "pad_fraction",
                  "packing_efficiency", "seq_per_sec", "step_time_ms",
                  "mfu"):
            v = _num(rec.get(k))
            if v is not None:
                out[f"{task}.{k}"] = v
    return out


# Latency percentiles are compared across rounds only where they are
# statistically meaningful: at rates the mode actually sustained
# (rate <= the saturation block's at_rate) and with enough 2xx samples
# to estimate the order statistic (p99 on an 80-request leg is the
# sample max — one scheduler hiccup away from any value). Past the
# saturation knee an OPEN-LOOP harness measures divergent queueing, not
# an SLO: the same binary at the same past-knee rate varies 4x
# run-to-run on the CPU harness (round 18 A/B), so gating there gates
# the phase of the moon. Overload-region percentiles stay fully indexed
# for the board — a genuine slowdown still trips the gated
# saturation.req_per_sec (the knee moves down) and the overload-region
# throughput keys, which stay gated at every rate.
_GATE_LATENCY_KEYS = ("p50_ms", "p95_ms", "p99_ms")
_GATE_MIN_SAMPLES = {"p50_ms": 0, "p95_ms": 100, "p99_ms": 200}


def serve_metrics(data: Dict[str, Any],
                  for_check: bool = False) -> Dict[str, float]:
    """Flat comparable metrics from a SERVE_*.json (tools/loadtest.py
    artifact): per mode x request-rate, the latency percentiles
    (lower-better), achieved throughput (req/s, real tokens/s) and batch
    occupancy (higher-better). Modes carrying a rate-sweep saturation
    block (round 17) additionally contribute `{label}.saturation.*`:
    saturation req/s and req/s-per-chip gate higher-better, the p99 at
    the saturation rate lower-better (the p99_ms marker), and the
    multi-replica speedup ratio vs the single-replica same-dtype mode
    higher-better — that ratio is the fleet-scale-out headline, so
    unlike the train-side step-time ratios it IS gated. Round 18 adds
    cost_per_1k_tokens (gated lower-better: the dollar view of
    occupancy + replica utilization) and, from the request-trace
    summary, the p99 tail's per-phase attribution (index-only).

    With for_check=True (the gate path), per-rate latency percentiles
    are emitted only for sustained, adequately-sampled rates (see
    _GATE_MIN_SAMPLES above); indexing keeps every rate."""
    out: Dict[str, float] = {}
    for label, mode in sorted((data.get("modes") or {}).items()):
        if not isinstance(mode, dict):
            continue
        sat_block = mode.get("saturation")
        sustain = _num(sat_block.get("at_rate")) \
            if isinstance(sat_block, dict) else None
        for rate, rec in sorted((mode.get("rates") or {}).items()):
            if not isinstance(rec, dict):
                continue
            try:
                rate_f = float(rate)
            except ValueError:
                rate_f = None
            n_2xx = _num(rec.get("n_2xx")) or 0.0
            for k in ("p50_ms", "p95_ms", "p99_ms", "req_per_sec",
                      "real_tokens_per_sec", "batch_occupancy",
                      "cost_per_1k_tokens"):
                if for_check and k in _GATE_LATENCY_KEYS:
                    overloaded = (sustain is not None and rate_f is not None
                                  and rate_f > sustain + 1e-9)
                    if overloaded or n_2xx < _GATE_MIN_SAMPLES[k]:
                        continue
                v = _num(rec.get(k))
                if v is not None:
                    out[f"{label}.r{rate}.{k}"] = v
        sat = mode.get("saturation")
        if isinstance(sat, dict):
            for k in ("req_per_sec", "p99_ms", "vs_single_replica",
                      "cost_per_1k_tokens"):
                v = _num(sat.get(k))
                if v is not None:
                    out[f"{label}.saturation.{k}"] = v
            meta = mode.get("meta") or {}
            chips = _num(meta.get("n_chips")) if isinstance(meta, dict) \
                else None
            rps = _num(sat.get("req_per_sec"))
            if chips and chips > 0 and rps is not None:
                out[f"{label}.saturation.req_per_sec_per_chip"] = \
                    rps / chips
        rts = mode.get("request_trace_summary")
        p99 = rts.get("p99") if isinstance(rts, dict) else None
        if isinstance(p99, dict):
            for phase, ms in sorted((p99.get("phase_ms") or {}).items()):
                v = _num(ms)
                if v is not None:
                    out[f"{label}.p99_attribution.{phase}_ms"] = v
            v = _num(p99.get("dominant_share"))
            if v is not None:
                out[f"{label}.p99_attribution.dominant_share"] = v
    return out


def distill_metrics(data: Dict[str, Any],
                    for_check: bool = False) -> Dict[str, float]:
    """Flat comparable metrics from a DISTILL_*.json (tools/loadtest.py
    --assemble --kind distill via scripts/distill_bench.sh). A distill
    artifact is SERVE-shaped — teacher/student serving legs under
    'modes', tagged by meta.model_tag — so every serving metric rides
    serve_metrics unchanged; on top, each leg contributes its task
    accuracy (higher-better), accuracy_delta vs the teacher (GATED
    lower-better via the accuracy_delta marker: the compression-broke-
    the-model regression class) and saturation.vs_teacher_per_chip, the
    distillation headline — student req/s-per-chip over the teacher's
    at the same p99 bound (higher-better)."""
    out = serve_metrics(data, for_check=for_check)
    for label, mode in sorted((data.get("modes") or {}).items()):
        if not isinstance(mode, dict):
            continue
        for k in ("accuracy", "accuracy_delta"):
            v = _num(mode.get(k))
            if v is not None:
                out[f"{label}.{k}"] = v
        sat = mode.get("saturation")
        if isinstance(sat, dict):
            v = _num(sat.get("vs_teacher_per_chip"))
            if v is not None:
                out[f"{label}.saturation.vs_teacher_per_chip"] = v
    return out


def graph_metrics(data: Dict[str, Any]) -> Dict[str, float]:
    """Flat comparable metrics from a tools/graphcheck.py
    results/graph_report.json: per-combo collective counts, donation
    health, sharded-input count, and the static per-device estimate —
    so program-structure trends ride the same board as the perf ones."""
    out: Dict[str, float] = {}
    for combo, rep in sorted((data.get("combos") or {}).items()):
        if not isinstance(rep, dict):
            continue
        for kind, n in sorted((rep.get("collective_counts") or {}).items()):
            v = _num(n)
            if v is not None:
                # zeros are kept on purpose: a kind growing 0 -> N is the
                # GSPMD-forked-collective regression class, and the gate
                # can only see it if the baseline records the zero
                out[f"{combo}.collectives.{kind}"] = v
        don = rep.get("donation") or {}
        for k in ("n_aliased", "n_donated_unaliased"):
            v = _num(don.get(k))
            if v is not None:
                name = ("donation_aliased" if k == "n_aliased"
                        else "donated_unaliased")
                out[f"{combo}.{name}"] = v
        inputs = rep.get("inputs")
        if isinstance(inputs, list):
            out[f"{combo}.sharded_inputs"] = float(sum(
                1 for r in inputs if isinstance(r, dict)
                and r.get("replicated") is False))
        # the SAME estimate the graphcheck memory pass gates (jax-free
        # import, like everything else this tool touches) — the board and
        # the gate must never disagree on what "device MB" means
        from bert_pytorch_tpu.analysis.passes import estimate_device_bytes

        est = estimate_device_bytes(rep)
        if est is not None:
            out[f"{combo}.est_device_mb"] = round(est / 2**20, 3)
    return out


def bench_metrics(data: Dict[str, Any]) -> Dict[str, float]:
    """Flat comparable metrics from a BENCH_*.json harness artifact."""
    out: Dict[str, float] = {}
    parsed = data.get("parsed")
    if isinstance(parsed, dict):
        renames = {"value": "seq128_seq_per_sec_per_chip",
                   "seq512_value": "seq512_seq_per_sec"}
        for k in ("value", "vs_baseline", "seq512_value", "seq512_mfu",
                  "seq512_vs_baseline"):
            v = _num(parsed.get(k))
            if v is not None:
                out[renames.get(k, k)] = v
    packing = data.get("packing")
    if isinstance(parsed, dict) and not isinstance(packing, dict):
        packing = parsed.get("packing")
    if isinstance(packing, dict):
        v = _num(packing.get("speedup_real_tokens_per_sec"))
        if v is not None:
            out["packing_speedup_real_tokens_per_sec"] = v
    # streaming-plane pair (scripts/input_bench.py --stream): tokenize
    # throughput + the vs-offline ratio are higher-better; the paced
    # starvation fraction carries "data_wait" and gates lower-better
    stream = data.get("stream")
    if isinstance(stream, dict):
        for k in ("tokens_per_sec", "hdf5_tokens_per_sec", "vs_hdf5",
                  "data_wait_fraction"):
            v = _num(stream.get(k))
            if v is not None:
                out[f"stream.{k}"] = v
    return out


def multichip_metrics(data: Dict[str, Any]) -> Dict[str, float]:
    """Flat comparable metrics from a MULTICHIP_*.json artifact: per-variant
    efficiency/throughput (dotted keys), the traced collective-time
    fraction (GATED lower-better — the round-15 quantity under attack)
    with its per-KIND split (absolute device-ms: index-only like every
    train-step time), and the cross-variant ratios."""
    out: Dict[str, float] = {}
    for label, v in sorted((data.get("variants") or {}).items()):
        if not isinstance(v, dict):
            continue
        for k in ("scaling_efficiency", "seqs_per_sec",
                  "seqs_per_sec_per_chip", "mfu", "step_time_ms"):
            val = _num(v.get(k))
            if val is not None:
                out[f"{label}.{k}"] = val
        tb = v.get("time_breakdown")
        if isinstance(tb, dict):
            cf = _num(tb.get("collective_fraction"))
            if cf is not None:
                out[f"{label}.collective_fraction"] = cf
            for kind, ms in sorted(
                    (tb.get("collective_kind_ms_per_step_device")
                     or {}).items()):
                val = _num(ms)
                if val is not None:
                    out[f"{label}.collective.{kind}_ms"] = val
    for k in ("zero1_step_time_ratio_vs_dp",
              "zero1_overlap_step_time_ratio_vs_zero1",
              "fsdp_overlap_step_time_ratio_vs_fsdp"):
        v = _num(data.get(k))
        if v is not None:
            out[k] = v
    return out


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def runlog_metrics(path: str) -> Dict[str, float]:
    """Summarize a MetricLogger jsonl: medians over its 'perf' interval
    records (plus the last packing efficiency — the steady-state value)."""
    perf: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("tag") == "perf":
                    perf.append(rec)
    except OSError:
        return {}
    if not perf:
        return {}
    out: Dict[str, float] = {"perf_intervals": float(len(perf))}
    steps = [_num(r.get("step")) for r in perf]
    steps = [s for s in steps if s is not None]
    if steps:
        out["last_step"] = max(steps)
    for k in ("step_time_ms", "seq_per_sec", "tokens_per_sec",
              "real_tokens_per_sec", "mfu", "data_wait_ms"):
        xs = [_num(r.get(k)) for r in perf]
        xs = [x for x in xs if x is not None]
        if xs:
            out[f"{k}_median"] = round(_median(xs), 6)
    for k in ("packing_efficiency", "pad_fraction"):
        xs = [_num(r.get(k)) for r in perf]
        xs = [x for x in xs if x is not None]
        if xs:
            out[k] = xs[-1]
    return out


def extract(path: str, for_check: bool = False
            ) -> Tuple[Optional[str], Dict[str, float],
                       Dict[str, Any]]:
    """(kind, metrics, raw) for one artifact file; kind None = not a perf
    artifact this tool understands. for_check=True is the gate's view:
    serve latency percentiles are restricted to sustained,
    adequately-sampled rates (see serve_metrics)."""
    if path.endswith(".jsonl"):
        return "runlog", runlog_metrics(path), {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"perfboard: unreadable artifact {path}: {e}")
    kind = detect_kind(data, path)
    if kind == "bench":
        return kind, bench_metrics(data), data
    if kind == "multichip":
        return kind, multichip_metrics(data), data
    if kind == "graph":
        return kind, graph_metrics(data), data
    if kind == "serve":
        return kind, serve_metrics(data, for_check=for_check), data
    if kind == "distill":
        return kind, distill_metrics(data, for_check=for_check), data
    if kind == "finetune":
        return kind, finetune_metrics(data), data
    return None, {}, data if isinstance(data, dict) else {}


# -- index --------------------------------------------------------------------


def index_records(root: str,
                  runs: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    for pattern, kind in (("BENCH_*.json", "bench"),
                          ("MULTICHIP_*.json", "multichip"),
                          ("SERVE_*.json", "serve"),
                          ("DISTILL_*.json", "distill"),
                          ("FINETUNE_*.json", "finetune"),
                          (os.path.join("results", "graph_report.json"),
                           "graph")):
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            _, metrics, raw = extract(path)
            rec: Dict[str, Any] = {
                "kind": kind,
                "artifact": os.path.basename(path),
                "round": _round_of(path),
                "ok": bool(raw.get("rc", 0) == 0
                           and raw.get("ok", True)),
                "measured": bool(metrics),
                "metrics": {k: metrics[k] for k in sorted(metrics)},
            }
            if kind == "multichip":
                rec["n_devices"] = raw.get("n_devices")
            if kind in ("serve", "distill"):
                # per-mode replicas/dtype meta (round 17 fleet serving);
                # only attached when the artifact carries it, so older
                # SERVE rounds index byte-identically
                meta = {lbl: mode["meta"]
                        for lbl, mode in sorted(
                            (raw.get("modes") or {}).items())
                        if isinstance(mode, dict)
                        and isinstance(mode.get("meta"), dict)}
                if meta:
                    rec["serve_modes"] = meta
                # p99 dominant-phase headline per mode (round 18 request
                # traces) — strings can't ride the numeric metrics dict,
                # so the table reads them from here; absent on older
                # artifacts, which therefore index byte-identically
                attr = {}
                for lbl, mode in sorted((raw.get("modes") or {}).items()):
                    if not isinstance(mode, dict):
                        continue
                    rts = mode.get("request_trace_summary")
                    p99 = rts.get("p99") if isinstance(rts, dict) else None
                    if isinstance(p99, dict) and p99.get("dominant_phase"):
                        attr[lbl] = {
                            "dominant_phase": p99["dominant_phase"],
                            "dominant_share": p99.get("dominant_share"),
                            "replica": p99.get("replica"),
                        }
                if attr:
                    rec["serve_attribution"] = attr
            records.append(rec)
    for pattern in runs or []:
        for path in sorted(glob.glob(pattern)):
            metrics = runlog_metrics(path)
            records.append({
                "kind": "runlog",
                "artifact": os.path.relpath(path, root)
                if path.startswith(root) else path,
                "round": None,
                "ok": bool(metrics),
                "measured": bool(metrics),
                "metrics": {k: metrics[k] for k in sorted(metrics)},
            })
    records.sort(key=lambda r: (r["kind"], r["round"] or 0, r["artifact"]))
    return records


def _md_cell(v: Optional[float], fmt: str = "{:.4g}") -> str:
    return fmt.format(v) if isinstance(v, (int, float)) else "—"


def _md_round(rec: Dict[str, Any]) -> str:
    """Row label: 'rNN' when the filename carried a round suffix, else the
    artifact name itself (a BENCH_baseline.json must not crash the index)."""
    if rec["round"] is not None:
        return f"r{rec['round']:02d}"
    return rec["artifact"]


def render_markdown(records: List[Dict[str, Any]]) -> str:
    """RUNS.md: the trend tables. Regenerated, never hand-edited."""
    lines = [
        "# RUNS — cross-round perf trend board",
        "",
        "Regenerated by `python tools/perfboard.py` from the checked-in",
        "`BENCH_*.json` / `MULTICHIP_*.json` artifacts (plus any `--runs`",
        "jsonl logs); the regression gate is `tools/perfboard.py --check`",
        "(see `scripts/check_perf.sh`). Do not edit by hand.",
        "",
        "## Bench (single-chip headline, BENCH_r*.json)",
        "",
        "| round | seq128 seq/s/chip | vs baseline | seq512 seq/s "
        "| seq512 MFU | packing speedup | stream tok/s | stream wait frac "
        "| ok |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in (x for x in records if x["kind"] == "bench"):
        m = r["metrics"]
        lines.append(
            f"| {_md_round(r)} "
            f"| {_md_cell(m.get('seq128_seq_per_sec_per_chip'))} "
            f"| {_md_cell(m.get('vs_baseline'))} "
            f"| {_md_cell(m.get('seq512_seq_per_sec'))} "
            f"| {_md_cell(m.get('seq512_mfu'))} "
            f"| {_md_cell(m.get('packing_speedup_real_tokens_per_sec'))} "
            f"| {_md_cell(m.get('stream.tokens_per_sec'))} "
            f"| {_md_cell(m.get('stream.data_wait_fraction'))} "
            f"| {'yes' if r['ok'] else 'NO'} |")
    lines += [
        "",
        "## Multichip (8-device mesh, MULTICHIP_r*.json; per-chip scaling "
        "efficiency vs single)",
        "",
        "| round | dp | dp_zero1 | dp_zero1_overlap | fsdp | fsdp_overlap "
        "| dp_seq | dp_seq_packing | dp_seq_packing_overlap "
        "| zero1/dp step ratio | overlap/zero1 step ratio "
        "| fsdp_overlap/fsdp step ratio | ok |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    mc_records = [x for x in records if x["kind"] == "multichip"]
    for r in mc_records:
        m = r["metrics"]
        eff = {lbl: m.get(f"{lbl}.scaling_efficiency")
               for lbl in ("dp", "dp_zero1", "dp_zero1_overlap", "fsdp",
                           "fsdp_overlap", "dp_seq", "dp_seq_packing",
                           "dp_seq_packing_overlap")}
        lines.append(
            f"| {_md_round(r)} "
            + "".join(f"| {_md_cell(eff[lbl])} " for lbl in eff)
            + f"| {_md_cell(m.get('zero1_step_time_ratio_vs_dp'))} "
            f"| {_md_cell(m.get('zero1_overlap_step_time_ratio_vs_zero1'))} "
            f"| {_md_cell(m.get('fsdp_overlap_step_time_ratio_vs_fsdp'))} "
            f"| {'yes' if r['ok'] else 'NO'} |")
    mc_frac = [r for r in mc_records
               if any(k.endswith(".collective_fraction")
                      for k in r["metrics"])]
    if mc_frac:
        variants = sorted({k.rsplit(".", 1)[0] for r in mc_frac
                           for k in r["metrics"]
                           if k.endswith(".collective_fraction")})
        lines += [
            "",
            "## Collective-time fraction per variant (traced; "
            "lower-better, gated by scripts/check_perf.sh)",
            "",
            "| round | " + " | ".join(variants) + " |",
            "|---|" + "---|" * len(variants),
        ]
        for r in mc_frac:
            m = r["metrics"]
            lines.append(
                f"| {_md_round(r)} "
                + "".join(
                    f"| {_md_cell(m.get(f'{v}.collective_fraction'))} "
                    for v in variants)
                + "|")
    graphs = [x for x in records if x["kind"] == "graph" and x["metrics"]]
    if graphs:
        lines += [
            "",
            "## Compiled-program structure (results/graph_report.json, "
            "tools/graphcheck.py)",
            "",
            "| combo | all-gather | all-reduce | reduce-scatter "
            "| aliased | sharded inputs | est device MB |",
            "|---|---|---|---|---|---|---|",
        ]
        combos = sorted({k.split(".", 1)[0]
                         for r in graphs for k in r["metrics"]})
        m = {k: v for r in graphs for k, v in r["metrics"].items()}
        for combo in combos:
            lines.append(
                f"| {combo} "
                f"| {_md_cell(m.get(f'{combo}.collectives.all-gather'), '{:.0f}')} "
                f"| {_md_cell(m.get(f'{combo}.collectives.all-reduce'), '{:.0f}')} "
                f"| {_md_cell(m.get(f'{combo}.collectives.reduce-scatter'), '{:.0f}')} "
                f"| {_md_cell(m.get(f'{combo}.donation_aliased'), '{:.0f}')} "
                f"| {_md_cell(m.get(f'{combo}.sharded_inputs'), '{:.0f}')} "
                f"| {_md_cell(m.get(f'{combo}.est_device_mb'))} |")
    serves = [x for x in records if x["kind"] == "serve" and x["metrics"]]
    if serves:
        lines += [
            "",
            "## Serving (SERVE_r*.json, tools/loadtest.py via "
            "scripts/serve_bench.sh)",
            "",
            "| round | mode @ rate | replicas | dtype | p50 ms | p95 ms "
            "| p99 ms | req/s | real tok/s | occupancy | ok |",
            "|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in serves:
            m = r["metrics"]
            modes_meta = r.get("serve_modes") or {}
            cells = sorted({k.rsplit(".", 1)[0] for k in m
                            if not k.rsplit(".", 1)[0]
                            .endswith((".saturation",
                                       ".p99_attribution"))})
            for cell in cells:
                meta = modes_meta.get(cell.rsplit(".r", 1)[0]) or {}
                lines.append(
                    f"| {_md_round(r)} "
                    f"| {cell.replace('.r', ' @ ')} "
                    f"| {_md_cell(meta.get('replicas'), '{:.0f}')} "
                    f"| {meta.get('dtype') or '—'} "
                    f"| {_md_cell(m.get(f'{cell}.p50_ms'))} "
                    f"| {_md_cell(m.get(f'{cell}.p95_ms'))} "
                    f"| {_md_cell(m.get(f'{cell}.p99_ms'))} "
                    f"| {_md_cell(m.get(f'{cell}.req_per_sec'))} "
                    f"| {_md_cell(m.get(f'{cell}.real_tokens_per_sec'))} "
                    f"| {_md_cell(m.get(f'{cell}.batch_occupancy'))} "
                    f"| {'yes' if r['ok'] else 'NO'} |")
        sat_rows = [(r, lbl) for r in serves
                    for lbl in sorted({k.split(".saturation.", 1)[0]
                                       for k in r["metrics"]
                                       if ".saturation." in k})]
        if sat_rows:
            lines += [
                "",
                "## Serving saturation (open-loop --rate_sweep: best "
                "req/s whose p99 stays under the bound; gated by "
                "scripts/check_perf.sh)",
                "",
                "| round | mode | replicas | dtype | sat req/s "
                "| req/s per chip | p99 @ sat ms | cost/1k tok "
                "| p99 dominant phase | vs 1-replica | ok |",
                "|---|---|---|---|---|---|---|---|---|---|---|",
            ]
            for r, lbl in sat_rows:
                m = r["metrics"]
                meta = (r.get("serve_modes") or {}).get(lbl) or {}
                attr = (r.get("serve_attribution") or {}).get(lbl) or {}
                if attr.get("dominant_phase"):
                    share = attr.get("dominant_share")
                    dom = attr["dominant_phase"]
                    if isinstance(share, (int, float)):
                        dom += f" {share:.0%}"
                    if attr.get("replica"):
                        dom += f" ({attr['replica']})"
                else:
                    dom = "—"
                lines.append(
                    f"| {_md_round(r)} "
                    f"| {lbl} "
                    f"| {_md_cell(meta.get('replicas'), '{:.0f}')} "
                    f"| {meta.get('dtype') or '—'} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.req_per_sec'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.req_per_sec_per_chip'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.p99_ms'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.cost_per_1k_tokens'))} "
                    f"| {dom} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.vs_single_replica'))} "
                    f"| {'yes' if r['ok'] else 'NO'} |")
    distills = [x for x in records
                if x["kind"] == "distill" and x["metrics"]]
    if distills:
        lines += [
            "",
            "## Distillation (DISTILL_r*.json, scripts/distill_bench.sh; "
            "teacher vs student legs at the same p99 bound, accuracy "
            "floor gated by `--check_distill`)",
            "",
            "| round | mode | model | dtype | sat req/s | req/s per chip "
            "| p99 @ sat ms | cost/1k tok | accuracy | Δ vs teacher "
            "| vs teacher/chip | ok |",
            "|---|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in distills:
            m = r["metrics"]
            labels = sorted({k.split(".", 1)[0] for k in m
                             if ".saturation." in k or ".accuracy" in k})
            for lbl in labels:
                meta = (r.get("serve_modes") or {}).get(lbl) or {}
                lines.append(
                    f"| {_md_round(r)} "
                    f"| {lbl} "
                    f"| {meta.get('model_tag') or '—'} "
                    f"| {meta.get('dtype') or '—'} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.req_per_sec'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.req_per_sec_per_chip'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.p99_ms'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.cost_per_1k_tokens'))} "
                    f"| {_md_cell(m.get(f'{lbl}.accuracy'))} "
                    f"| {_md_cell(m.get(f'{lbl}.accuracy_delta'))} "
                    f"| {_md_cell(m.get(f'{lbl}.saturation.vs_teacher_per_chip'))} "
                    f"| {'yes' if r['ok'] else 'NO'} |")
    finetunes = [x for x in records
                 if x["kind"] == "finetune" and x["metrics"]]
    if finetunes:
        lines += [
            "",
            "## Finetune (FINETUNE_r*.json, run_finetune.py "
            "--perf_artifact; per registered task)",
            "",
            "| round | task | real tok/s | pad frac | packing eff "
            "| seq/s | step ms | MFU |",
            "|---|---|---|---|---|---|---|---|",
        ]
        for r in finetunes:
            m = r["metrics"]
            tasks = sorted({k.split(".", 1)[0] for k in m})
            for task in tasks:
                lines.append(
                    f"| {_md_round(r)} "
                    f"| {task} "
                    f"| {_md_cell(m.get(f'{task}.real_tokens_per_sec'))} "
                    f"| {_md_cell(m.get(f'{task}.pad_fraction'))} "
                    f"| {_md_cell(m.get(f'{task}.packing_efficiency'))} "
                    f"| {_md_cell(m.get(f'{task}.seq_per_sec'))} "
                    f"| {_md_cell(m.get(f'{task}.step_time_ms'))} "
                    f"| {_md_cell(m.get(f'{task}.mfu'))} |")
    runlogs = [x for x in records if x["kind"] == "runlog" and x["metrics"]]
    if runlogs:
        lines += [
            "",
            "## Run logs (--runs)",
            "",
            "| log | last step | step ms (med) | seq/s (med) | MFU (med) "
            "| packing eff | data wait ms (med) |",
            "|---|---|---|---|---|---|---|",
        ]
        for r in runlogs:
            m = r["metrics"]
            lines.append(
                f"| {r['artifact']} "
                f"| {_md_cell(m.get('last_step'), '{:.0f}')} "
                f"| {_md_cell(m.get('step_time_ms_median'))} "
                f"| {_md_cell(m.get('seq_per_sec_median'))} "
                f"| {_md_cell(m.get('mfu_median'))} "
                f"| {_md_cell(m.get('packing_efficiency'))} "
                f"| {_md_cell(m.get('data_wait_ms_median'))} |")
    return "\n".join(lines) + "\n"


def write_index(root: str, out_path: str, md_path: str,
                runs: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    records = index_records(root, runs=runs)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)) or ".",
                exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True, allow_nan=False) + "\n")
    with open(md_path, "w", encoding="utf-8") as f:
        f.write(render_markdown(records))
    return records


# -- check --------------------------------------------------------------------


def check_artifacts(baseline_path: str, current_path: str,
                    tolerance: float) -> Tuple[List[str], List[str]]:
    """Returns (regressions, notes). Regressions non-empty => gate fails."""
    bk, base, _ = extract(baseline_path, for_check=True)
    ck, cur, _ = extract(current_path, for_check=True)
    if not base:
        raise SystemExit(
            f"perfboard: no comparable metrics in baseline {baseline_path}")
    if not cur:
        raise SystemExit(
            f"perfboard: no comparable metrics in current {current_path}")
    if bk != ck:
        raise SystemExit(
            f"perfboard: artifact kinds differ ({bk} vs {ck}) — comparing "
            "a bench headline against a multichip sweep is not a gate")
    regressions: List[str] = []
    notes: List[str] = []
    for name in sorted(base):
        direction = metric_direction(name)
        if direction is None:
            continue
        b = base[name]
        if name not in cur:
            notes.append(f"MISSING: {name} (baseline {b:g}) absent from "
                         "current artifact")
            continue
        c = cur[name]
        if direction == "nonzero":
            # reduce-scatter: gate only the count collapsing back to zero
            # (the rs path silently reverting to all-reduce); any nonzero
            # movement — including appearing from zero — is fine
            if b > 0 and c == 0:
                regressions.append(
                    f"REGRESSION: {name}: baseline {b:g} -> current 0 "
                    f"(reduce-scatter path disappeared — grads are back "
                    f"on the all-reduce-then-slice path)")
            else:
                notes.append(f"ok: {name}: baseline {b:g} -> current "
                             f"{c:g} (nonzero-gated)")
            continue
        if b == 0:
            # relative deltas are undefined at a zero baseline, but a
            # lower-is-better metric MOVING OFF zero is an absolute
            # regression (a collective kind appearing from nowhere, pad
            # creeping into an unpadded run) — never skip it silently
            if c > 0 and direction == "lower":
                regressions.append(
                    f"REGRESSION: {name}: baseline 0 -> current {c:g} "
                    f"(lower-is-better metric left zero)")
            continue
        delta = (c - b) / abs(b)
        regressed = (delta < -tolerance if direction == "higher"
                     else delta > tolerance)
        line = (f"{name}: baseline {b:g} -> current {c:g} "
                f"({delta:+.1%}, {direction}-is-better, "
                f"tolerance {tolerance:.0%})")
        if regressed:
            regressions.append("REGRESSION: " + line)
        else:
            notes.append("ok: " + line)
    return regressions, notes


DEFAULT_DISTILL_MAX_DELTA = 0.05


def check_distill(path: str, max_delta: float
                  ) -> Tuple[List[str], List[str]]:
    """Accuracy-floor gate over ONE distill artifact: every student leg
    (meta.model_tag set and != 'teacher') must carry an accuracy_delta
    (teacher accuracy minus its own) no larger than max_delta.
    Direction-aware: a student BEATING its teacher (delta <= 0) passes
    by any margin; only quality lost to compression trips. A student
    leg with no delta recorded fails loudly — an unmeasured student is
    not a passing student. Returns (failures, notes)."""
    kind, _, raw = extract(path)
    if kind != "distill":
        raise SystemExit(
            f"perfboard: {path} is kind {kind!r}, not a distill artifact "
            "(tools/loadtest.py --assemble --kind distill)")
    failures: List[str] = []
    notes: List[str] = []
    students = 0
    for label, mode in sorted((raw.get("modes") or {}).items()):
        if not isinstance(mode, dict):
            continue
        tag = str((mode.get("meta") or {}).get("model_tag") or "")
        if not tag or tag == "teacher":
            continue
        students += 1
        delta = _num(mode.get("accuracy_delta"))
        if delta is None:
            failures.append(
                f"GATE: student leg '{label}' ({tag}) carries no "
                "accuracy_delta — unmeasured students do not pass")
        elif delta > max_delta:
            failures.append(
                f"GATE: student leg '{label}' ({tag}) lost {delta:g} "
                f"accuracy vs its teacher (> floor {max_delta:g})")
        else:
            notes.append(
                f"ok: '{label}' ({tag}) accuracy_delta {delta:g} "
                f"<= {max_delta:g}"
                + (" (beats teacher)" if delta < 0 else ""))
    if students == 0:
        failures.append(
            "GATE: no student legs (modes with meta.model_tag != "
            "'teacher') in artifact — nothing to gate")
    return failures, notes


# -- CLI ----------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=None,
                    help="repo root to scan for BENCH_*/MULTICHIP_* "
                         "(default: this tool's repo)")
    ap.add_argument("--out", default=None,
                    help="index jsonl path (default <root>/results/"
                         "runs.jsonl)")
    ap.add_argument("--md", default=None,
                    help="trend table path (default <root>/RUNS.md)")
    ap.add_argument("--runs", nargs="*", default=None,
                    help="additional MetricLogger jsonl globs to index")
    ap.add_argument("--check", nargs=2, default=None,
                    metavar=("BASELINE", "CURRENT"),
                    help="regression gate between two artifacts of the "
                         "same kind; exit 1 naming each regressed metric")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="relative wrong-direction move that fails the "
                         "gate (default 0.1 = 10%%)")
    ap.add_argument("--check_distill", default=None, metavar="DISTILL_JSON",
                    help="accuracy-floor gate over one distill artifact: "
                         "exit 1 if any student leg lost more than "
                         "--distill_max_delta accuracy vs the teacher "
                         "(or carries no measured delta)")
    ap.add_argument("--distill_max_delta", type=float,
                    default=DEFAULT_DISTILL_MAX_DELTA,
                    help="largest tolerated teacher-minus-student "
                         "accuracy drop (default "
                         f"{DEFAULT_DISTILL_MAX_DELTA}); students that "
                         "beat the teacher always pass")
    ap.add_argument("--quiet", action="store_true",
                    help="check mode: print regressions only")
    args = ap.parse_args(argv)

    if args.check_distill:
        failures, notes = check_distill(args.check_distill,
                                        args.distill_max_delta)
        if not args.quiet:
            for n in notes:
                print(n)
        for f in failures:
            print(f)
        if failures:
            print(f"perfboard: distill accuracy gate FAILED "
                  f"({len(failures)} problem(s), floor "
                  f"{args.distill_max_delta:g}, {args.check_distill})")
            return 1
        print(f"perfboard: distill accuracy gate ok (floor "
              f"{args.distill_max_delta:g}, {args.check_distill})")
        return 0

    if args.check:
        regressions, notes = check_artifacts(args.check[0], args.check[1],
                                             args.tolerance)
        if not args.quiet:
            for n in notes:
                print(n)
        for r in regressions:
            print(r)
        if regressions:
            print(f"perfboard: {len(regressions)} metric(s) regressed "
                  f"beyond {args.tolerance:.0%} "
                  f"({args.check[0]} -> {args.check[1]})")
            return 1
        print(f"perfboard: no regression beyond {args.tolerance:.0%} "
              f"({args.check[0]} -> {args.check[1]})")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    out = args.out or os.path.join(root, "results", "runs.jsonl")
    md = args.md or os.path.join(root, "RUNS.md")
    records = write_index(root, out, md, runs=args.runs)
    measured = sum(1 for r in records if r["measured"])
    print(f"perfboard: indexed {len(records)} artifact(s) "
          f"({measured} with metrics) -> {out}, {md}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
