#!/usr/bin/env python
"""Deterministic step replay from a flight-recorder bundle.

A training run that hits a non-finite step (or dies) dumps a repro bundle
(telemetry/flight_recorder.py): the last K loader batches, the per-dispatch
PRNG keys, the recent metric tail, and a manifest carrying everything the
train step was built from. This tool closes the loop:

  python tools/replay.py --bundle <dir>              # reproduce
  python tools/replay.py --bundle <dir> --bisect     # name the guilty scope
  python tools/replay.py --bundle <dir> --validate   # schema check only

Replay restores the newest checkpoint whose gap to the offending step the
bundle's records cover, re-executes those steps with the EXACT step program
the run used — same builders, same optimizer/schedule construction
(run_pretraining.make_optimizer), same accum math, same packed-field
threading, same mesh when the local device count allows — and asserts the
recorded loss/health flags reproduce bit-identically. Works on CPU
(JAX_PLATFORMS=cpu) against bundles recorded on TPU: the program is the
same, only the backend differs (bitwise equality is asserted when recording
and replay platforms match; across backends expect agreement to float
tolerance and identical flags).

--bisect re-runs the offending step's forward microbatch-by-microbatch on a
model with config.debug_taps=True and reports the first tensor to go
non-finite in execution order (embeddings -> layer_i/attention ->
layer_i/mlp -> pooler -> mlm_head -> nsp_head), across stacked and
unstacked layouts. If every forward scope is finite but gradients were
flagged, the blowup is in the backward pass and the per-group
grad_nonfinite_* counts localize it.

Exit codes (script mode): 0 reproduced / valid, 1 mismatch, 2 bundle or
schema error.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.telemetry.flight_recorder import (  # noqa: E402
    validate_bundle)

# metric keys that are pure functions of (restored state, recorded batches,
# recorded rng) and therefore must reproduce BIT-identically. The EMA-carried
# signals (grad_norm_ema/z, grad_spike, param_norm_drift) are excluded by
# design: TelemetryState is ephemeral (stripped from checkpoints), so replay
# re-warms it from zero exactly like a live resume does.
DETERMINISTIC_KEYS = (
    "loss", "grad_norm", "param_norm", "mlm_accuracy", "learning_rate",
    "loss_nonfinite", "grad_nonfinite", "skipped_nonfinite", "mlm_dropped",
)


class ReplayError(RuntimeError):
    """Bundle unusable: schema, coverage, or checkpoint problems."""


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--bundle", required=True, type=str,
                   help="repro bundle directory (manifest.json + "
                        "batches.npz)")
    p.add_argument("--step", type=int, default=None,
                   help="step to reproduce (default: the manifest's "
                        "trigger_step)")
    p.add_argument("--checkpoint", type=str, default=None,
                   help="checkpoint dir override (default: the manifest's "
                        "recorded checkpoint dir — override when the "
                        "bundle moved machines)")
    p.add_argument("--bisect", action="store_true",
                   help="after reproducing, re-run the offending step's "
                        "forward with per-named_scope taps and report the "
                        "first non-finite tensor")
    p.add_argument("--validate", action="store_true",
                   help="schema-check the bundle manifest + npz and exit "
                        "(no jax, no checkpoint needed)")
    p.add_argument("--stacked_params", type=str, default="auto",
                   choices=["auto", "true", "false"],
                   help="encoder layout override; 'auto' replays the "
                        "layout the bundle recorded. The checkpoint "
                        "restores across layouts either way "
                        "(restore_either_layout)")
    return p.parse_args(argv)


def _load_manifest(bundle: str) -> dict:
    path = os.path.join(bundle, "manifest.json")
    if not os.path.isfile(path):
        raise ReplayError(f"no manifest.json under {bundle}")
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except Exception as e:
        raise ReplayError(f"manifest.json unreadable: {e}")


def _batch_for(npz, rec) -> dict:
    return {k: npz[f"s{rec['step']:08d}__{k}"] for k in rec["fields"]}


def _rng_for(npz, rec):
    return npz[f"s{rec['step']:08d}__rng"]


def _values_equal(a: float, b: float) -> bool:
    if math.isnan(a) and math.isnan(b):
        return True  # both NaN: the non-finiteness reproduced
    return a == b


def _order_taps(taps) -> list:
    """Flatten a 'debug_taps' collection into [(scope_name, array), ...] in
    forward-execution order, across both encoder layouts. Stacked taps
    (bert/encoder/layers/layer/*) carry a leading L axis and are split
    into per-layer entries; unstacked taps live under layer_{i}."""
    entries = []

    def walk(tree, path):
        if isinstance(tree, dict):
            for k, v in tree.items():
                walk(v, path + (str(k),))
        else:
            vals = tree if isinstance(tree, (tuple, list)) else (tree,)
            for v in vals:
                entries.append((path, np.asarray(v)))

    walk(taps, ())

    ordered = []
    for path, arr in entries:
        if "embeddings_out" in path:
            ordered.append(((0, 0, 0), "embeddings", arr))
            continue
        sub = (0 if "attention_out" in path
               else 1 if "mlp_out" in path else None)
        if sub is not None:
            layer = next((int(p.split("_", 1)[1]) for p in path
                          if p.startswith("layer_")
                          and p.split("_", 1)[1].isdigit()), None)
            name = "attention" if sub == 0 else "mlp"
            if layer is not None:  # unstacked: per-layer modules
                ordered.append(((1, layer, sub), f"layer_{layer}/{name}",
                                arr))
            else:  # stacked: leading (L, ...) axis from nn.scan
                for i in range(arr.shape[0]):
                    ordered.append(((1, i, sub), f"layer_{i}/{name}",
                                    arr[i]))
            continue
        if "pooled" in path:
            ordered.append(((2, 0, 0), "pooler", arr))
        elif "mlm_logits" in path:
            ordered.append(((3, 0, 0), "mlm_head", arr))
        elif "nsp_logits" in path:
            ordered.append(((4, 0, 0), "nsp_head", arr))
    ordered.sort(key=lambda t: t[0])
    return [(name, arr) for _, name, arr in ordered]


def main(argv=None) -> dict:
    args = parse_arguments(argv)
    bundle = args.bundle

    errors = validate_bundle(bundle)
    if args.validate:
        for e in errors:
            print(f"INVALID: {e}")
        if not errors:
            print(f"bundle {bundle}: manifest schema v-ok, arrays "
                  "cross-checked")
        return {"valid": not errors, "errors": errors}
    if errors:
        raise ReplayError("bundle failed schema validation: "
                          + "; ".join(errors))

    manifest = _load_manifest(bundle)
    run = manifest["run"]
    npz = np.load(os.path.join(bundle, "batches.npz"))

    stream = manifest.get("stream")
    if isinstance(stream, dict):
        # streaming-plane bundle (manifest schema-v2 optional key): the
        # recorded batches came from tokenize-on-the-fly ingestion. Replay
        # itself needs no source access — the batches are in the npz — but
        # the operator re-pointing the plane does, so name the exact
        # corpus records the window covers.
        windows = [w for w in stream.get("recent_batches") or []
                   if isinstance(w, dict)]
        span = ""
        if windows:
            lo = min(w["record_lo"] for w in windows)
            hi = max(w["record_hi"] for w in windows)
            span = (f"; recorded batches cover global records {lo}..{hi} "
                    "(global_seq numbering across all sources)")
        cursor = stream.get("cursor") or {}
        print(f"streaming-mode bundle: {len(stream.get('sources') or [])} "
              f"sources (hash {stream.get('sources_hash')}), cursor at "
              f"epoch {cursor.get('epoch')} source {cursor.get('source')} "
              f"record {cursor.get('record')} "
              f"(global_seq {cursor.get('global_seq')}){span}",
              file=sys.stderr)

    import jax

    jax.config.update("jax_default_prng_impl",
                      run.get("rng_impl", "threefry2x32"))
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    from bert_pytorch_tpu.telemetry import HealthConfig, init_telemetry_state
    from bert_pytorch_tpu.training import (CheckpointManager,
                                           build_pretrain_step,
                                           make_sharded_state)
    from bert_pytorch_tpu.training.pretrain import (StepProgram,
                                                    build_debug_forward,
                                                    chain_steps,
                                                    inject_nonfinite,
                                                    stack_microbatches)
    from run_pretraining import make_optimizer

    cfg = BertConfig.from_dict(manifest["model_config"])
    if args.stacked_params != "auto":
        cfg = cfg.replace(stacked_params=(args.stacked_params == "true"))
    compute_dtype = (jnp.bfloat16 if cfg.dtype == "bfloat16"
                     else jnp.float32)
    model = BertForPreTraining(cfg, dtype=compute_dtype)

    schedule = schedulers.make_schedule(
        run["lr_decay"], run["learning_rate"], run["max_steps"],
        warmup=run["warmup_proportion"],
        offset=run["previous_phase_end_step"])
    # round-16 run-block key (absent in older bundles -> "off"): the
    # fused multi-tensor update must rebuild, or the replayed program's
    # fingerprint would diverge from the recorded run
    tx = make_optimizer(run["optimizer"], schedule,
                        fused=run.get("fused_optim", "off"))

    # same mesh as the run when this machine can host it; otherwise pure-DP
    # over whatever devices exist (cross-shape replay stays deterministic,
    # but reduction orders may differ from the recorded run — warn)
    want_mesh = {k: int(v) for k, v in run["mesh"].items()}
    mesh_size = int(np.prod(list(want_mesh.values()) or [1]))
    same_mesh = mesh_size == jax.device_count()
    mesh = mesh_lib.make_mesh(want_mesh if same_mesh else None)
    if not same_mesh:
        print(f"WARNING: recorded mesh {want_mesh} needs {mesh_size} "
              f"devices, have {jax.device_count()}; replaying on "
              f"{dict(mesh.shape)} — flags will reproduce, bitwise "
              "equality may not", file=sys.stderr)

    records = {r["step"]: r for r in manifest["records"]}
    target = args.step if args.step is not None else manifest["trigger_step"]
    if target not in records:
        raise ReplayError(
            f"step {target} not in the bundle (recorded steps: "
            f"{sorted(records)})")
    recorded = next((m for m in manifest["metrics_tail"]
                     if m.get("step") == target), None)

    ckpt_dir = args.checkpoint or manifest["checkpoint"]["dir"]
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        raise ReplayError(
            f"checkpoint dir {ckpt_dir!r} not found — pass --checkpoint")
    manager = CheckpointManager(ckpt_dir)
    try:
        steps_avail = manager.all_steps()
        base = next((c for c in sorted(steps_avail, reverse=True)
                     if c < target
                     and all(s in records
                             for s in range(c + 1, target + 1))), None)
        if base is None:
            raise ReplayError(
                f"no checkpoint covers step {target}: checkpoints "
                f"{steps_avail}, recorded steps {sorted(records)} — the "
                "recorder window did not reach back to a checkpoint "
                "(raise --recorder_window or checkpoint more often)")
        if records[base + 1]["pos"] != 0:
            raise ReplayError(
                f"replay would start mid-dispatch at step {base + 1} "
                "(--steps_per_loop chunk partially evicted from the ring)")

        health = (HealthConfig(action=run["nonfinite_action"])
                  if run["health_pack"] == "on" else None)
        grad_dtype = (jnp.bfloat16 if run["grad_dtype"] == "bfloat16"
                      else None)
        accum = int(run["accum_steps"])
        inject_step = run.get("inject_nonfinite_step")

        first_batch = _batch_for(npz, records[base + 1])
        stacked0 = stack_microbatches(first_batch, accum)

        def init_fn(rng):
            return model.init(rng,
                              jnp.asarray(stacked0["input_ids"][0]),
                              jnp.asarray(stacked0["token_type_ids"][0]),
                              jnp.asarray(stacked0["attention_mask"][0]))

        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(run["seed"]), init_fn, tx, mesh=mesh,
                zero1=bool(run.get("zero1")),
                zero1_params=bool(run.get("zero1_overlap")))

        zero1_plan = None
        if run.get("zero1"):
            from bert_pytorch_tpu.parallel.zero import make_zero1_plan

            # zero1_rs is recorded from the plan (not the flag), so a
            # same-mesh replay rebuilds the psum_scatter exit exactly; on
            # a cross-shape fallback mesh rs may be unsupportable — drop
            # it rather than refuse the replay (values are identical by
            # the rs parity tests; only the collective schedule differs)
            from bert_pytorch_tpu.parallel.zero import rs_supported

            want_rs = bool(run.get("zero1_rs"))
            if want_rs and not rs_supported(mesh):
                print("WARNING: recorded run used zero1_rs but the "
                      f"replay mesh {dict(mesh.shape)} cannot host it; "
                      "replaying on the all-reduce path", file=sys.stderr)
                want_rs = False
            zero1_plan = make_zero1_plan(
                state.params, shardings.params, mesh,
                gather_on_use=bool(run.get("zero1_overlap")) or want_rs,
                reduce_scatter=want_rs,
                warn_skipped=False)

        # round-15 run-block keys (absent in older bundles -> falsy):
        # rebuild the fsdp gather-on-use plan and the coalesced-reduction
        # machinery exactly as run_pretraining wired them, or the replayed
        # program's collective structure (and the fingerprint compare)
        # would diverge from the recorded run
        plan = zero1_plan
        if run.get("fsdp_overlap"):
            from bert_pytorch_tpu.parallel.zero import make_fsdp_plan

            fplan = make_fsdp_plan(state.params, shardings.params, mesh,
                                   zero1=zero1_plan is not None,
                                   warn_skipped=False)
            if fplan is not None:
                plan = fplan
        norm_reducer = None
        if run.get("coalesce_reductions") and plan is not None:
            from bert_pytorch_tpu.parallel.coalesce import NormReducer

            norm_reducer = NormReducer(plan.grad_shardings, mesh)
            tx = make_optimizer(run["optimizer"], schedule,
                                norm_reducer=norm_reducer,
                                fused=run.get("fused_optim", "off"))

        if run.get("kfac"):
            from bert_pytorch_tpu.optim.kfac import KFAC, KFACConfig
            from bert_pytorch_tpu.training import init_kfac_state
            from bert_pytorch_tpu.training.pretrain import \
                build_kfac_pretrain_step

            kcfg = run["kfac"]
            cfg = cfg.replace(kfac_taps=True)
            model = BertForPreTraining(cfg, dtype=compute_dtype)
            kfac = KFAC(KFACConfig(
                inv_interval=kcfg["inv_interval"],
                factor_interval=kcfg["factor_interval"],
                stat_decay=kcfg["stat_decay"],
                damping=kcfg["damping"],
                kl_clip=kcfg["kl_clip"],
                skip_layers=tuple(kcfg["skip_layers"]),
                learning_rate=schedule,
                stats_dtype=(jnp.bfloat16
                             if kcfg.get("stats_dtype") == "bf16"
                             else None)),
                mesh=mesh if mesh_lib.data_shard_count(mesh) > 1 else None,
                factor_bucket_bytes=kcfg.get("factor_bucket_bytes"),
                factor_sync_freq=kcfg.get("factor_sync_freq", 1))
            state, pert_template = init_kfac_state(
                model, kfac, state,
                (stacked0["input_ids"][0], stacked0["token_type_ids"][0],
                 stacked0["attention_mask"][0]))
            step_fn = build_kfac_pretrain_step(
                model, tx, kfac, pert_template, schedule=schedule,
                accum_steps=accum, max_predictions=run["max_pred_row"],
                grad_dtype=grad_dtype, zero1=plan, health=health,
                nan_inject_step=inject_step, norm_reducer=norm_reducer)
        else:
            step_fn = build_pretrain_step(
                model, tx, schedule=schedule, accum_steps=accum,
                max_predictions=run["max_pred_row"],
                grad_dtype=grad_dtype, zero1=plan, health=health,
                nan_inject_step=inject_step, norm_reducer=norm_reducer)

        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), state)

        def restore():
            s, _extra, _step = manager.restore_either_layout(abstract,
                                                             step=base)
            if health is not None:
                s = s.replace(telemetry=init_telemetry_state())
            return s

        # StepProgram keeps the compiled HLO reachable, so the replayed
        # program's fingerprint can be compared against the recorded one
        jit_step = StepProgram(step_fn)
        jit_chunks = {}

        def replay_steps(state, stop_before_target: bool):
            """Re-execute base+1..target, dispatch-faithfully: single
            steps through jit_step, --steps_per_loop chunks through the
            same chain_steps program the run used. Returns (state,
            final-step metrics) — with stop_before_target, returns the
            state ENTERING the target step instead (for bisect)."""
            metrics = None
            s = base + 1
            while s <= target:
                rec = records[s]
                n = rec["n_steps"]
                if stop_before_target and s == target and n == 1:
                    return state, None
                rng = jnp.asarray(_rng_for(npz, rec))
                if n == 1:
                    stacked = stack_microbatches(_batch_for(npz, rec),
                                                 accum)
                    batch = mesh_lib.host_to_device_batch(mesh, stacked)
                    state, metrics = jit_step(state, batch, rng)
                    s += 1
                    continue
                d0 = s - rec["pos"]
                last = d0 + n - 1
                if rec["pos"] != 0 or any(
                        i not in records
                        for i in range(d0, min(last, target) + 1)):
                    raise ReplayError(
                        f"steps {d0}..{last} form one --steps_per_loop "
                        "dispatch; the ring evicted part of it — cannot "
                        "replay dispatch-faithfully")
                if last > target or (stop_before_target
                                     and last == target):
                    # the target lands INSIDE this dispatch (--step on an
                    # inner chunk step — the sticky chunk metrics live on
                    # the final step, but the bad batch may not), or
                    # bisect needs the state entering it: walk the inner
                    # steps with the single-step program (numerically the
                    # same body the fori_loop ran), keys by fold_in(rng, i)
                    end = target - 1 if stop_before_target else target
                    for i in range(end - d0 + 1):
                        inner = records[d0 + i]
                        stacked = stack_microbatches(
                            _batch_for(npz, inner), accum)
                        batch = mesh_lib.host_to_device_batch(mesh,
                                                              stacked)
                        state, metrics = jit_step(
                            state, batch, jax.random.fold_in(rng, i))
                    if stop_before_target:
                        return state, None
                    return state, metrics
                chunk = {
                    k: np.stack([
                        stack_microbatches(_batch_for(npz,
                                                      records[d0 + i]),
                                           accum)[k]
                        for i in range(n)])
                    for k in records[d0]["fields"]}
                if n not in jit_chunks:
                    jit_chunks[n] = StepProgram(
                        chain_steps(step_fn, n, per_step_batch=True))
                batch = mesh_lib.host_to_device_batch(mesh, chunk,
                                                      n_leading=2)
                state, metrics = jit_chunks[n](state, batch, rng)
                s = last + 1
            return state, metrics

        with mesh, mesh_lib.logical_rules():
            _, metrics = replay_steps(restore(),
                                      stop_before_target=False)
        replayed = {k: float(v) for k, v in metrics.items()}

        result = {
            "step": target,
            "base_checkpoint": base,
            "replayed": replayed,
            "recorded": recorded,
            "match": None,
            "mismatches": [],
        }

        # program-structure check (manifest schema-v2 extension): the run
        # recorded its compiled step's fingerprint; compare it against the
        # program THIS replay compiled. A divergence means the replay is
        # faithfully re-running a structurally different program — values
        # may still match, but any conclusion about collectives/donation
        # drawn here would not transfer back to the recorded run.
        recorded_fp = manifest.get("program_fingerprint")
        replayed_fp = None
        if isinstance(recorded_fp, dict):
            from bert_pytorch_tpu.analysis.hlo import compare_fingerprints

            want = int(recorded_fp.get("steps_per_loop", 1))
            prog = jit_chunks.get(want) if want > 1 else jit_step
            f = prog.fingerprint() if prog is not None else None
            if f is not None:
                replayed_fp = dict(f, steps_per_loop=want)
            comparable, fp_diffs = compare_fingerprints(recorded_fp,
                                                        replayed_fp)
            result["program_fingerprint"] = {
                "recorded": recorded_fp, "replayed": replayed_fp,
                "match": (comparable and not fp_diffs) if replayed_fp
                else None,
                "diffs": fp_diffs,
            }
            if replayed_fp is None:
                print("program fingerprint: recorded but the replay's "
                      f"{want}-step program was not AOT-compiled — "
                      "structure not compared", file=sys.stderr)
            elif not comparable:
                print("program fingerprint: not comparable ("
                      + "; ".join(fp_diffs) + ") — cross-backend/mesh "
                      "replay, structure differences are expected",
                      file=sys.stderr)
            elif fp_diffs:
                print("WARNING: replayed program structure DIVERGES from "
                      "the recorded run:", file=sys.stderr)
                for d in fp_diffs:
                    print(f"  {d}", file=sys.stderr)
            else:
                print(f"program fingerprint: replayed program matches the "
                      f"recorded one ({recorded_fp.get('hash')})",
                      file=sys.stderr)
        if recorded is None:
            print(f"step {target}: no recorded metrics in the bundle tail "
                  "(crash before readback, or an inner --steps_per_loop "
                  "step — the chunk's sticky metrics live on its final "
                  "step) — replayed values reported, nothing to compare "
                  "against", file=sys.stderr)
        else:
            keys = [k for k in DETERMINISTIC_KEYS if k in recorded] + \
                [k for k in sorted(recorded)
                 if k.startswith("grad_nonfinite_")]
            for k in keys:
                if k not in replayed:
                    result["mismatches"].append(
                        {"key": k, "recorded": recorded[k],
                         "replayed": None})
                    continue
                if not _values_equal(float(recorded[k]),
                                     float(replayed[k])):
                    result["mismatches"].append(
                        {"key": k, "recorded": float(recorded[k]),
                         "replayed": float(replayed[k])})
            result["match"] = not result["mismatches"]
            verdict = ("REPRODUCED bit-identically" if result["match"]
                       else "MISMATCH")
            print(f"step {target} (from checkpoint {base}): {verdict} "
                  f"(loss={replayed.get('loss')}, loss_nonfinite="
                  f"{replayed.get('loss_nonfinite')}, grad_nonfinite="
                  f"{replayed.get('grad_nonfinite')})")
            for m in result["mismatches"]:
                print(f"  {m['key']}: recorded {m['recorded']} != "
                      f"replayed {m['replayed']}")

        if args.bisect:
            with mesh, mesh_lib.logical_rules():
                state2, _ = replay_steps(restore(),
                                         stop_before_target=True)
                params_in = jax.tree.map(np.asarray, state2.params)
            rec = records[target]
            rng = jnp.asarray(_rng_for(npz, rec))
            inner = (jax.random.fold_in(rng, rec["pos"])
                     if rec["n_steps"] > 1 else rng)
            rngs = jax.random.split(inner, accum)
            stacked = stack_microbatches(_batch_for(npz, rec), accum)
            params_probe = params_in
            if inject_step == target:
                params_probe = inject_nonfinite(params_in,
                                                jnp.asarray(True))
            dbg_model = BertForPreTraining(cfg.replace(debug_taps=True),
                                           dtype=compute_dtype)
            fwd = jax.jit(build_debug_forward(
                dbg_model, max_predictions=run["max_pred_row"]))
            first_bad = None
            scopes = []
            for i in range(accum):
                micro = {k: jnp.asarray(v[i]) for k, v in stacked.items()}
                loss_i, taps = fwd(params_probe, micro, rngs[i])
                for name, arr in _order_taps(taps):
                    finite = bool(np.isfinite(np.asarray(arr)).all())
                    if i == 0:
                        scopes.append({"scope": name, "finite": finite})
                    if not finite and first_bad is None:
                        first_bad = {"scope": name, "microbatch": i}
                if first_bad is not None:
                    break
                if not math.isfinite(float(loss_i)) and first_bad is None:
                    first_bad = {"scope": "loss", "microbatch": i}
                    break
            if first_bad is None and float(
                    replayed.get("grad_nonfinite", 0)) > 0:
                groups = {k: v for k, v in replayed.items()
                          if k.startswith("grad_nonfinite_") and v > 0}
                first_bad = {"scope": "backward", "microbatch": None,
                             "grad_groups": groups}
            result["bisect"] = {"first_nonfinite": first_bad,
                                "scopes": scopes}
            if first_bad is None:
                print("bisect: every forward scope finite, no non-finite "
                      "gradients — nothing to blame at this step")
            else:
                where = first_bad["scope"]
                mb = first_bad.get("microbatch")
                print(f"bisect: first non-finite tensor in scope "
                      f"'{where}'"
                      + (f" (microbatch {mb})" if mb is not None else "")
                      + (f" — grad groups {first_bad['grad_groups']}"
                         if "grad_groups" in first_bad else ""))
        return result
    finally:
        manager.close()


def _cli(argv=None) -> int:
    try:
        result = main(argv)
    except ReplayError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    if result.get("valid") is False:
        return 2
    if result.get("match") is False:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(_cli())
