#!/usr/bin/env python
"""int8 serving quantization accuracy gate (offline CLI).

`run_server.py --serve_dtype int8` quantizes weights at restore time and
refuses to serve past `--int8_max_delta`; this tool runs the SAME check
offline so an operator can qualify a checkpoint before rollout — and
prove the gate actually trips on a broken quantization:

    # qualify: quantize each checkpoint, compare the int8 decode against
    # the f32 reference on a deterministic probe batch, gate the delta
    python tools/quantcheck.py --model_config_file cfg.json \
        --task_checkpoint squad=out/squad_ckpt \
        --task_checkpoint classify=out/classify_ckpt \
        --class_names 0 1 --max_delta 0.1

    # negative control: corrupt one leaf's scales — MUST exit nonzero
    python tools/quantcheck.py ... --inject broken_scale

Exit 0 = every task under the gate; exit 1 = at least one task over it
(or, with --inject, the corruption somehow slipped under the gate —
which would mean the gate is broken). --out writes the per-task report
as JSON for CI artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_config_file", required=True, type=str)
    p.add_argument("--task_checkpoint", action="append", required=True,
                   metavar="TASK=DIR")
    p.add_argument("--labels", type=str, nargs="+", default=None)
    p.add_argument("--class_names", type=str, nargs="+",
                   default=["negative", "positive"])
    p.add_argument("--num_choices", type=int, default=2)
    p.add_argument("--embed_labels", type=int, default=2)
    p.add_argument("--max_segments", type=int, default=4)
    p.add_argument("--max_delta", type=float, default=0.1,
                   help="gate: max relative decode delta vs f32")
    p.add_argument("--bucket", type=int, default=64,
                   help="probe batch sequence length")
    p.add_argument("--batch_rows", type=int, default=2)
    p.add_argument("--vocab_pad_multiple", type=int, default=8)
    p.add_argument("--inject", type=str, default="none",
                   choices=["none", "broken_scale"],
                   help="broken_scale: corrupt one quantized leaf's "
                        "scales — the gate MUST trip (negative control)")
    p.add_argument("--out", type=str, default=None,
                   help="write the per-task JSON report here")
    p.add_argument("--force_cpu", action="store_true")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import threading

    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.serving import quantize as quant_lib
    from bert_pytorch_tpu.serving.engine import restore_serving_params
    from bert_pytorch_tpu.tasks import registry

    checkpoints = {}
    for entry in args.task_checkpoint:
        task, sep, ckpt = entry.partition("=")
        if not sep or task not in registry.all_tasks():
            raise SystemExit(f"--task_checkpoint wants TASK=DIR with a "
                             f"registered task, got {entry!r}")
        checkpoints[task] = ckpt

    config = BertConfig.from_json_file(args.model_config_file)
    config = config.replace(vocab_size=pad_vocab_size(
        config.vocab_size, args.vocab_pad_multiple))
    bucket = min(args.bucket, config.max_position_embeddings)
    serve_opts = {
        "tok_lock": threading.Lock(),
        "labels": args.labels,
        "class_names": args.class_names,
        "num_choices": args.num_choices,
        "embed_labels": args.embed_labels,
        "max_segments": args.max_segments,
    }
    probe = quant_lib.probe_batch(args.batch_rows, bucket,
                                  config.vocab_size,
                                  max_segments=min(2, args.max_segments))

    report, failed = {}, []
    for task in sorted(checkpoints):
        spec = registry.get(task)
        ref_model = spec.build_serving_model(config, jnp.float32,
                                             serve_opts)
        params, step = restore_serving_params(
            checkpoints[task], ref_model, bucket, log=lambda m: None)
        qparams, stats = quant_lib.quantize_tree(jax.device_get(params))
        if args.inject == "broken_scale":
            qparams = quant_lib.corrupt_scales(qparams)
        serve_model = spec.build_serving_model(config, jnp.bfloat16,
                                               serve_opts)
        q_forward = quant_lib.wrap_forward(
            spec.forward_builder(serve_model), jnp.bfloat16)
        delta = quant_lib.decode_delta(
            spec.forward_builder(ref_model), params, q_forward, qparams,
            probe)
        ok = delta["rel_delta"] <= args.max_delta
        if not ok:
            failed.append(task)
        report[task] = {
            "checkpoint": checkpoints[task], "step": step,
            "quantized_leaves": stats["quantized_leaves"],
            "bytes_before": stats["bytes_before"],
            "bytes_after": stats["bytes_after"],
            "inject": args.inject, "ok": ok,
            **{k: float(v) for k, v in delta.items()},
        }
        print(f"quantcheck[{task}]: rel_delta {delta['rel_delta']:.4f} "
              f"(gate {args.max_delta:g}) argmax_agreement "
              f"{delta['argmax_agreement']:.4f} "
              f"{stats['bytes_before'] / 1e6:.1f}->"
              f"{stats['bytes_after'] / 1e6:.1f} MB "
              + ("OK" if ok else "FAIL"))

    doc = {"schema_version": 1, "kind": "quantcheck",
           "max_delta": args.max_delta, "inject": args.inject,
           "tasks": report, "ok": not failed}
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
            f.write("\n")
    if failed:
        print(f"quantcheck: FAIL — task(s) over the gate: "
              f"{', '.join(failed)}")
        return 1
    print("quantcheck: all tasks under the gate")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
