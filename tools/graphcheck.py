#!/usr/bin/env python
"""Graph lint: static analysis of the compiled train steps as a CI gate.

The program-structure bug class — fail-open sharding gates (round 7),
GSPMD forking the ZeRO-1 gather into extra all-gathers (round 11),
silently-dropped buffer donation — is invisible to unit tests until a
multichip bench runs. This tool lowers + compiles the PRODUCTION step
builders (build_pretrain_step / build_kfac_pretrain_step, the exact
functions run_pretraining wires) for a named set of config x mesh combos
on a forced 8-device CPU mesh — no TPU, no bench run — parses the
compiled HLO into structured reports (bert_pytorch_tpu/analysis/hlo.py),
and diffs them against checked-in budgets with the rule framework
(analysis/passes.py):

  python tools/graphcheck.py
      # build reports for every combo, write results/graph_report.json,
      # diff against results/graph_budgets.json; exit 1 naming each
      # error finding (rule, op, leaf). scripts/check_graph.sh wraps this.

  python tools/graphcheck.py --combos pretrain_dp8,zero1_dp8
      # subset (tier-1 tests use this to stay fast)

  python tools/graphcheck.py --write-budgets
      # re-baseline: derive results/graph_budgets.json from the current
      # programs. Run after an INTENTIONAL program change, commit both
      # files, and say why in the commit message.

  python tools/graphcheck.py --validate-budgets
      # jax-free (login host / CI front door, mirrors tools/perfboard.py):
      # schema-check the budget file, and when results/graph_report.json
      # exists diff it against the budgets without recompiling anything.

  python tools/graphcheck.py --combos zero1_dp8 --inject no_donate
      # regression drill: compile a deliberately-broken program
      # (no_donate drops donate_argnums; replicated_state builds the
      # TrainState with the ZeRO-1 storage sharding failed open;
      # extra_gather adds one unbudgeted all-gather; wrong_axis derives
      # ONE leaf's expected spec with a deliberately swapped mesh axis so
      # the sharding_rules pass must exit 1 naming the rule, the leaf,
      # and both shardings) and prove the gate exits nonzero naming the
      # rule — tests/test_graph_analysis.py + tests/test_sharding_rules.py
      # pin this.

Every expectation the sharding_rules pass gates is DERIVED from the
logical-axis-rules table (bert_pytorch_tpu/parallel/rules.py — the one
source of truth for params, ZeRO-1 moments, K-FAC factors, batch inputs,
and the serving engine's per-bucket specs; docs/SHARDING.md), never
hand-written per combo.

Exit codes: 0 clean, 1 findings with severity=error, 2 unusable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bert_pytorch_tpu.analysis import passes as passes_mod  # noqa: E402

BUDGETS_SCHEMA_VERSION = 1
DEFAULT_BUDGETS = os.path.join(REPO, "results", "graph_budgets.json")
DEFAULT_REPORT = os.path.join(REPO, "results", "graph_report.json")

N_DEVICES = 8

# combo name -> step-builder variant. One entry per production program
# shape worth gating: the plain DP step, the bf16-compute step (dtype
# lint), the two ZeRO-1 modes (collective budgets + replication), the
# K-FAC step (its factor state is exactly what a fail-open gate silently
# replicates), a mixed dp x mp mesh (the composition the pre-rules
# ad-hoc specs never covered: zero1's appended data axis stacking onto
# model-sharded leaves), and one bucketed serving forward (kind="serve":
# the AOT inference program run_server.py dispatches — a single-device
# engine must compile ZERO collectives, and nothing may sit in the
# donated-but-never-aliased table). `mesh` overrides the default
# all-data 8-device shape. hbm_budget_mb is the per-device
# static-estimate ceiling for the tiny gate model — generous vs today's
# estimate, tight vs a 2x regression.
COMBOS = {
    "pretrain_dp8": dict(zero1=False, overlap=False, kfac=False,
                         dtype="f32", hbm_budget_mb=64),
    "pretrain_bf16_dp8": dict(zero1=False, overlap=False, kfac=False,
                              dtype="bf16", hbm_budget_mb=64),
    "zero1_dp8": dict(zero1=True, overlap=False, kfac=False,
                      dtype="f32", hbm_budget_mb=64),
    "zero1_overlap_dp8": dict(zero1=True, overlap=True, kfac=False,
                              dtype="f32", hbm_budget_mb=64),
    "zero1_dp2_mp4": dict(zero1=True, overlap=False, kfac=False,
                          dtype="f32", hbm_budget_mb=64,
                          mesh={"data": 2, "model": 4}),
    # fsdp gather-on-use (--fsdp_overlap) composed with the zero1 overlap
    # on a mixed dp x fsdp mesh: every point-of-use gather is an explicit
    # per-leaf node, with the collective budget an exact ceiling (the
    # GSPMD-fork regression class this gate exists for)
    "fsdp_overlap_dp2_fsdp4": dict(zero1=True, overlap=True, kfac=False,
                                   dtype="f32", hbm_budget_mb=64,
                                   mesh={"data": 2, "fsdp": 4},
                                   fsdp_overlap=True),
    "kfac_zero1_dp8": dict(zero1=True, overlap=False, kfac=True,
                           dtype="f32", hbm_budget_mb=96),
    # coalesced reductions (--coalesce_reductions): bucketed K-FAC factor
    # psums + bucketed LAMB trust/global norms. Its budget's all-reduce
    # ceiling is deliberately <= HALF of kfac_zero1_dp8's — the round-15
    # acceptance criterion, enforced as an exact count like every budget
    "kfac_zero1_dp8_bucketed": dict(zero1=True, overlap=False, kfac=True,
                                    dtype="f32", hbm_budget_mb=96,
                                    bucketed=True),
    # reduce-scatter gradient path (--zero1_rs): the ZeRO-1 update
    # consumes a psum_scatter'd gradient SHARD instead of slicing a full
    # all-reduce (half the gradient bytes on the wire). Requires
    # gather-on-use (overlap) and coalesced norms (bucketed) — without
    # the NormReducer the shard_map region's per-leaf trust norms would
    # blow the all-reduce count right back up. The round-16 acceptance
    # criterion rides on this budget: reduce_scatter > 0 AND all-reduce
    # <= HALF of zero1_dp8's 129, enforced as exact counts
    "zero1_rs_dp8": dict(zero1=True, overlap=True, kfac=False,
                         dtype="f32", hbm_budget_mb=64, rs=True,
                         bucketed=True),
    "kfac_zero1_rs_dp8": dict(zero1=True, overlap=True, kfac=True,
                              dtype="f32", hbm_budget_mb=96, rs=True,
                              bucketed=True),
    # 8 layers so the stacked-factor axis DIVIDES the dp8 shard count —
    # the only combo where K-FAC leaves carry sharding_rules
    # expectations (the 2-layer gate model's factors fall back to
    # replicated by the divisibility rule, which would leave K-FAC
    # placement unverified everywhere)
    "kfac_zero1_l8_dp8": dict(zero1=True, overlap=False, kfac=True,
                              dtype="f32", hbm_budget_mb=96, layers=8),
    "serve_qa_b4_s64": dict(kind="serve", dtype="f32", batch_rows=4,
                            bucket=64, hbm_budget_mb=32),
    # per-segment pooled classification forward (registry task
    # 'classify'): the first segment-kind serving program under the
    # lint — the pooled gather must stay collective-free and
    # donation-clean exactly like the token-kind QA forward
    "serve_cls_b4_s64": dict(kind="serve", task="classify", dtype="f32",
                             batch_rows=4, bucket=64, hbm_budget_mb=32),
    # model-parallel serving slice (run_server --serve_mesh model=2):
    # params shard through the SAME rules-table derivation the engine
    # uses (serving_param_shardings), so this forward legitimately
    # carries collectives — its budget pins exact NONZERO per-kind
    # ceilings, growing the serve lint beyond the single-device
    # zero-collective pin while the 1-dev combos keep theirs
    "serve_qa_b4_s64_mp2": dict(kind="serve", dtype="f32", batch_rows=4,
                                bucket=64, hbm_budget_mb=32,
                                mesh={"model": 2}),
    # the shared finetune driver's packed classification train step
    # (build_pretrain_step + tasks/classify.packed_loss_builder — the
    # exact production program run_finetune.py --task classify --packing
    # dispatches), with sharding-rules expectations derived from the
    # logical-axis-rules table for the registry task's batch contract
    "finetune_cls_dp8": dict(kind="finetune", dtype="f32",
                             hbm_budget_mb=64),
}

INJECTIONS = ("none", "no_donate", "replicated_state", "extra_gather",
              "extra_allreduce", "wrong_axis")


# -- jax-free: budget schema + diff -------------------------------------------


def load_json(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"graphcheck: unreadable {path}: {e}")


def validate_budgets(budgets: dict) -> list:
    """Schema errors in a budget file (empty list = valid). Pure dict
    work — runs without jax."""
    errors = []
    if not isinstance(budgets, dict):
        return ["budget file is not a JSON object"]
    if budgets.get("schema_version") != BUDGETS_SCHEMA_VERSION:
        errors.append(f"schema_version {budgets.get('schema_version')!r} "
                      f"!= {BUDGETS_SCHEMA_VERSION}")
    combos = budgets.get("combos")
    if not isinstance(combos, dict) or not combos:
        return errors + ["'combos' missing or empty"]
    for name, combo in sorted(combos.items()):
        expect = combo.get("expect") if isinstance(combo, dict) else None
        if not isinstance(expect, dict):
            errors.append(f"combo '{name}': no 'expect' object")
            continue
        unknown = set(expect) - set(passes_mod.PASSES)
        if unknown:
            errors.append(f"combo '{name}': unknown expectation key(s) "
                          f"{sorted(unknown)}")
        cb = expect.get("collective_budget")
        if cb is not None:
            if not isinstance(cb, dict):
                errors.append(f"combo '{name}': collective_budget is not "
                              "an object")
            else:
                for kind, v in cb.items():
                    if not isinstance(v, int) or v < 0:
                        errors.append(
                            f"combo '{name}': collective_budget[{kind}] = "
                            f"{v!r} (want a non-negative int)")
        sr = expect.get("sharding_rules")
        if sr is not None:
            if not isinstance(sr, dict):
                errors.append(f"combo '{name}': sharding_rules is not "
                              "an object")
            else:
                mv = sr.get("min_verified")
                if not isinstance(mv, int) or mv < 0:
                    errors.append(
                        f"combo '{name}': sharding_rules.min_verified = "
                        f"{mv!r} (want a non-negative int)")
    return errors


def diff_reports(reports: dict, budgets: dict) -> dict:
    """{combo: [Finding]} for every combo present in BOTH the report set
    and the budget file; a combo missing from either side is reported as a
    finding on the side that has it (a silently-skipped combo is how gates
    rot)."""
    out = {}
    bcombos = budgets.get("combos", {})
    for name in sorted(set(reports) | set(bcombos)):
        if name not in reports:
            out[name] = [passes_mod.Finding(
                "warning", "coverage",
                "combo is budgeted but no report was built for it "
                "(--combos subset?)")]
            continue
        if name not in bcombos:
            out[name] = [passes_mod.Finding(
                "error", "coverage",
                "combo has a report but no checked-in budget — run "
                "graphcheck --write-budgets and commit the result")]
            continue
        out[name] = passes_mod.run_passes(
            reports[name], bcombos[name].get("expect", {}))
    return out


def print_findings(per_combo: dict, stream=None) -> int:
    """Human gate output; returns the number of error-severity findings."""
    stream = stream or sys.stdout
    n_err = 0
    for name in sorted(per_combo):
        findings = per_combo[name]
        if not findings:
            print(f"graphcheck: {name}: clean", file=stream)
            continue
        for f in findings:
            if f.severity == "error":
                n_err += 1
            print(f"graphcheck: {name}: {f}", file=stream)
    return n_err


def budgets_from_reports(reports: dict, meta: dict) -> dict:
    """Derive a budget file locking in the current programs: exact
    collective counts per kind (zero stays zero — a brand-new collective
    kind is a finding), the donation floor, the sharded-input floor, the
    combo's dtype expectation, and its HBM ceiling."""
    combos = {}
    for name, rep in sorted(reports.items()):
        spec = COMBOS.get(name, {})
        inputs = rep.get("inputs") or []
        n_sharded = sum(1 for r in inputs
                        if r.get("replicated") is False)
        n_verified = sum(1 for r in inputs
                         if r.get("matches_expected") is not None)
        donation_expect = {
            "min_aliased": rep.get("donation", {}).get("n_aliased", 0),
            "undonated_warn_bytes": 8 * 2**20,
        }
        n_orphans = rep.get("donation", {}).get("n_donated_unaliased", 0)
        if n_orphans:
            # budgeted orphan-donor allowance (passes.check_donation) —
            # emitted ONLY when nonzero so clean combos' budget blocks
            # stay byte-identical and keep the strict default
            donation_expect["max_donated_unaliased"] = n_orphans
        expect = {
            "collective_budget": dict(
                sorted(rep.get("collective_counts", {}).items())),
            "donation": donation_expect,
            "replication": {"min_sharded_inputs": n_sharded},
            "sharding_rules": {"min_verified": n_verified},
            "dtype": {"compute_dtype": spec.get("dtype", "f32"),
                      "max_f32_dots": (rep.get("dot_dtypes") or {}
                                       ).get("f32", 0)},
            "memory": {"budget_mb": spec.get("hbm_budget_mb", 64)},
        }
        combos[name] = {"expect": expect}
    return {"schema_version": BUDGETS_SCHEMA_VERSION, **meta,
            "combos": combos}


# -- jax side: build the reports ----------------------------------------------


def _force_cpu_devices() -> None:
    """Script entry only (tests inherit conftest's setup): force the
    8-device CPU host platform BEFORE jax initializes."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _gate_config(dtype: str, kfac: bool, layers: int = 2):
    """The tiny-but-production-shaped gate model: every structural feature
    of the real step (tied embeddings, NSP head, gathered MLM head, LAMB,
    ZeRO-1) at compile-in-seconds scale. Structure, not scale, is what the
    gate checks. `layers` matters to the K-FAC combos: distributed factor
    ownership only engages when the stacked-layer axis divides the shard
    count (kfac_zero1_l8_dp8)."""
    from bert_pytorch_tpu.config import BertConfig

    return BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=layers,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, next_sentence=True,
        dtype="bfloat16" if dtype == "bf16" else "float32",
        fused_ops=False, attention_impl="xla",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        kfac_taps=kfac)


def _gate_batch(vocab: int = 128, global_batch: int = 16, seq: int = 16,
                max_pred: int = 4):
    """Deterministic synthetic premasked batch (exactly max_pred masked
    positions per row — the gathered-MLM-head contract)."""
    import numpy as np

    from bert_pytorch_tpu.training.pretrain import stack_microbatches

    rng = np.random.RandomState(0)
    ids = rng.randint(5, vocab, (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -1, np.int32)
    for b in range(global_batch):
        for p in rng.choice(np.arange(1, seq - 1), max_pred, replace=False):
            labels[b, p] = ids[b, p]
            ids[b, p] = 3
    return stack_microbatches({
        "input_ids": ids,
        "token_type_ids": np.zeros((global_batch, seq), np.int32),
        "attention_mask": np.ones((global_batch, seq), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (global_batch,)).astype(
            np.int32),
    }, 1)


# the serve_opts the gate hands the registry specs (run_server CLI
# defaults at gate-model scale; graphcheck's serve combos must build the
# same model heads production serving builds)
GATE_SERVE_OPTS = {"labels": ["B-X", "I-X", "O"],
                   "class_names": ["0", "1"], "num_choices": 2,
                   "embed_labels": 2, "max_segments": 4}


def build_serve_report(name: str, spec: dict, inject: str = "none") -> dict:
    """Lower + compile one bucketed serving forward — the PRODUCTION
    inference program (the registry task's forward_builder through the
    same StepProgram the engine dispatches) on a single device, exactly
    as a 1-dev run_server.py engine compiles it — or, with
    `spec['mesh']` (e.g. {"model": 2}), exactly as a `--serve_mesh`
    replica slice compiles it: params placed by the rules-table-derived
    `serving_param_shardings`, so the budget pins NONZERO per-kind
    collective ceilings. `spec['task']` names any tasks/registry.py
    entry (default squad); the single-device budget pins zero
    collectives of every kind and an empty donated-unaliased table."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.analysis.hlo import program_report
    from bert_pytorch_tpu.serving.engine import (bucket_input_expectations,
                                                 serving_param_shardings,
                                                 zero_batch)
    from bert_pytorch_tpu.tasks import registry as task_registry
    from bert_pytorch_tpu.training.pretrain import StepProgram
    from bert_pytorch_tpu.training.state import unbox

    if inject != "none":
        raise SystemExit(
            f"graphcheck: injection '{inject}' drills the pretrain "
            "combos; run it with --combos zero1_dp8 (or another "
            "pretrain combo)")

    cfg = _gate_config(spec["dtype"], kfac=False).replace(
        next_sentence=False)
    compute_dtype = jnp.bfloat16 if spec["dtype"] == "bf16" else jnp.float32
    tspec = task_registry.get(spec.get("task", "squad"))
    model = tspec.build_serving_model(cfg, compute_dtype, GATE_SERVE_OPTS)
    bucket, rows = int(spec["bucket"]), int(spec["batch_rows"])
    sample = jnp.zeros((1, bucket), jnp.int32)
    params = unbox(model.init(jax.random.PRNGKey(0), sample, sample,
                              sample)["params"])

    mesh = None
    if spec.get("mesh"):
        from jax.sharding import NamedSharding

        from bert_pytorch_tpu.parallel import rules as rules_lib
        from bert_pytorch_tpu.parallel.mesh import make_mesh

        n_dev = 1
        for v in spec["mesh"].values():
            n_dev *= int(v)
        if jax.device_count() < n_dev:
            raise SystemExit(
                f"graphcheck: combo {name} needs {n_dev} devices, "
                f"have {jax.device_count()}")
        mesh = make_mesh(dict(spec["mesh"]), devices=jax.devices()[:n_dev])
        shardings, _ = serving_param_shardings(model, bucket, mesh)
        params = jax.device_put(params, shardings)
        batch = jax.device_put(
            zero_batch(rows, bucket),
            NamedSharding(mesh, rules_lib.batch_spec(0, mesh)))
    else:
        batch = {k: jnp.asarray(v)
                 for k, v in zero_batch(rows, bucket).items()}

    prog = StepProgram(tspec.forward_builder(model), donate_state=False)
    lowered = prog.lower(params, batch)
    lowered_text = lowered.as_text()
    compiled = prog.compile()

    # the engine's per-bucket specs, derived from the rules table (on
    # the single-device engine: everything replicated; on a serve mesh:
    # model-sharded mlp/heads/vocab leaves — derived, not hand-pinned),
    # verified against the compiled in-shardings by the sharding_rules
    # pass
    expected, exp_rules = bucket_input_expectations(model, bucket, mesh)
    rep = program_report(compiled, args=(params, batch),
                         expected=expected, rules=exp_rules,
                         lowered_text=lowered_text, label=name)
    rep["combo"] = dict(spec, inject=inject)
    return rep


def build_finetune_report(name: str, spec: dict,
                          inject: str = "none") -> dict:
    """Lower + compile the shared finetune driver's PACKED classification
    train step on the 8-device mesh — build_pretrain_step wired with
    tasks/classify.packed_loss_builder, fed a batch assembled by the
    SAME packer + registry label packer the driver uses
    (training/finetune.pack_finetune_batch + classify.pack_labels), so
    the gated batch contract is registry-derived rather than
    hand-written. step_input_expectations verifies every input leaf
    against the logical-axis-rules table (sharding_rules pass)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bert_pytorch_tpu.analysis.hlo import program_report
    from bert_pytorch_tpu.models import BertForSequenceClassification
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.adam import fused_adam
    from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    from bert_pytorch_tpu.tasks import classify
    from bert_pytorch_tpu.training import make_sharded_state
    from bert_pytorch_tpu.training.finetune import pack_finetune_batch
    from bert_pytorch_tpu.training.pretrain import (StepProgram,
                                                    build_pretrain_step,
                                                    step_input_expectations)
    from bert_pytorch_tpu.training.state import abstract_train_state

    if inject != "none":
        raise SystemExit(
            f"graphcheck: injection '{inject}' drills the pretrain "
            "combos; run it with --combos zero1_dp8 (or another "
            "pretrain combo)")
    if jax.device_count() < N_DEVICES:
        raise SystemExit(
            f"graphcheck: {jax.device_count()} devices visible, need "
            f"{N_DEVICES}")

    cfg = _gate_config(spec["dtype"], kfac=False)
    compute_dtype = jnp.bfloat16 if spec["dtype"] == "bf16" else jnp.float32
    G, rows, seq = 4, 16, 16
    model = BertForSequenceClassification(cfg, num_labels=2,
                                          max_segments=G,
                                          dtype=compute_dtype)
    sched = schedulers.poly_warmup_schedule(1e-4, total_steps=100,
                                            warmup=0.1)
    import optax

    tx = optax.chain(
        optax.clip_by_global_norm(1.0),
        fused_adam(sched, weight_decay=0.01,
                   weight_decay_mask=default_weight_decay_mask,
                   bias_correction=False))

    # deterministic synthetic pair-classification examples, packed by
    # the production packer (first-fit, per-segment labels)
    rng_np = np.random.RandomState(0)
    n_ex = 48
    lens = 3 + rng_np.randint(0, seq - 3, n_ex)
    arrays = {
        "input_ids": np.zeros((n_ex, seq), np.int32),
        "token_type_ids": np.zeros((n_ex, seq), np.int32),
        "attention_mask": np.zeros((n_ex, seq), np.int32),
        "labels": rng_np.randint(0, 2, n_ex).astype(np.int32),
    }
    for i, ln in enumerate(lens):
        arrays["input_ids"][i, :ln] = rng_np.randint(5, cfg.vocab_size, ln)
        arrays["token_type_ids"][i, ln // 2:ln] = 1
        arrays["attention_mask"][i, :ln] = 1
    batch_fields, placements = pack_finetune_batch(
        arrays, list(range(n_ex)), n_rows=rows, seq_len=seq,
        max_segments=G)
    batch_fields.update(classify.pack_labels(arrays, placements, rows,
                                             seq, G))
    batch_np = {k: v[None] for k, v in batch_fields.items()}  # (1, B, ..)

    mesh = mesh_lib.make_mesh(spec.get("mesh"),
                              devices=jax.devices()[:N_DEVICES])
    sample = jnp.zeros((2, seq), jnp.int32)

    def init_fn(r):
        return model.init(r, sample, sample, sample)

    with mesh_lib.logical_rules():
        state, _shardings = make_sharded_state(
            jax.random.PRNGKey(0), init_fn, tx, mesh=mesh)
    step_fn = build_pretrain_step(
        model, tx, schedule=sched,
        loss_fn_builder=classify.packed_loss_builder)

    batch = mesh_lib.host_to_device_batch(mesh, batch_np)
    rng = jax.random.PRNGKey(0)
    prog = StepProgram(step_fn)
    with mesh, mesh_lib.logical_rules():
        lowered = prog.lower(state, batch, rng)
        lowered_text = lowered.as_text()
        compiled = prog.compile()

    with mesh_lib.logical_rules():
        abstract = abstract_train_state(jax.random.PRNGKey(0), init_fn, tx)
    expected, exp_rules = step_input_expectations(abstract, state, batch,
                                                  mesh)
    rep = program_report(compiled, args=(state, batch, rng),
                         expected=expected, rules=exp_rules,
                         lowered_text=lowered_text, label=name)
    rep["combo"] = dict(spec, inject=inject)
    return rep


def build_report(name: str, spec: dict, inject: str = "none") -> dict:
    """Lower + compile one combo's production step on the 8-device mesh
    and return its program report. `inject` compiles a deliberately
    broken program for gate drills (see module docstring)."""
    import jax
    import jax.numpy as jnp

    if spec.get("kind") == "serve":
        return build_serve_report(name, spec, inject=inject)
    if spec.get("kind") == "finetune":
        return build_finetune_report(name, spec, inject=inject)

    from bert_pytorch_tpu.analysis.hlo import program_report
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import (default_trust_batch_axes,
                                             default_weight_decay_mask, lamb)
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    from bert_pytorch_tpu.parallel.zero import make_zero1_plan
    from bert_pytorch_tpu.training import make_sharded_state
    from bert_pytorch_tpu.training.pretrain import (StepProgram,
                                                    build_pretrain_step,
                                                    step_input_expectations)
    from bert_pytorch_tpu.training.state import abstract_train_state

    if jax.device_count() < N_DEVICES:
        raise SystemExit(
            f"graphcheck: {jax.device_count()} devices visible, need "
            f"{N_DEVICES} (set XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={N_DEVICES})")
    if inject not in INJECTIONS:
        raise SystemExit(f"graphcheck: unknown injection '{inject}'")

    cfg = _gate_config(spec["dtype"], spec["kfac"],
                       layers=spec.get("layers", 2))
    compute_dtype = jnp.bfloat16 if spec["dtype"] == "bf16" else jnp.float32
    grad_dtype = jnp.bfloat16 if spec["dtype"] == "bf16" else None
    model = BertForPreTraining(cfg, dtype=compute_dtype)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100,
                                            warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)
    batch_np = _gate_batch(vocab=cfg.vocab_size)
    mesh = mesh_lib.make_mesh(spec.get("mesh"),
                              devices=jax.devices()[:N_DEVICES])

    def init_fn(r):
        return model.init(r, jnp.asarray(batch_np["input_ids"][0]),
                          jnp.asarray(batch_np["token_type_ids"][0]),
                          jnp.asarray(batch_np["attention_mask"][0]))

    # `replicated_state` drill: the TrainState is built with the ZeRO-1
    # storage sharding FAILED OPEN (the PR-2 bug class) while the plan and
    # the budget still expect it — the replication pass must name the
    # replicated moment leaves.
    state_zero1 = spec["zero1"] and inject != "replicated_state"
    with mesh_lib.logical_rules():
        state, shardings = make_sharded_state(
            jax.random.PRNGKey(0), init_fn, tx, mesh=mesh,
            zero1=state_zero1,
            zero1_params=spec["overlap"] and state_zero1)

    plan = (make_zero1_plan(state.params, shardings.params, mesh,
                            gather_on_use=spec["overlap"] and state_zero1,
                            reduce_scatter=spec.get("rs", False)
                            and state_zero1,
                            warn_skipped=False)
            if spec["zero1"] else None)
    if spec.get("fsdp_overlap"):
        from bert_pytorch_tpu.parallel.zero import make_fsdp_plan

        plan = make_fsdp_plan(state.params, shardings.params, mesh,
                              zero1=plan is not None,
                              warn_skipped=False) or plan

    norm_reducer = None
    if spec.get("bucketed") and plan is not None:
        # the --coalesce_reductions wiring, exactly as run_pretraining
        # builds it: one NormReducer shared by LAMB and the grad_norm
        # metric, built from the SAME layout tree the plan derived
        from bert_pytorch_tpu.parallel.coalesce import NormReducer

        norm_reducer = NormReducer(plan.grad_shardings, mesh)
        tx = lamb(sched, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes,
                  norm_reducer=norm_reducer)

    kfac = None
    if spec["kfac"]:
        from bert_pytorch_tpu.optim.kfac import KFAC, KFACConfig
        from bert_pytorch_tpu.training.pretrain import (
            build_kfac_pretrain_step, init_kfac_state)

        kfac = KFAC(KFACConfig(learning_rate=sched), mesh=mesh,
                    factor_bucket_bytes=(4 << 20) if spec.get("bucketed")
                    else None)
        state, pert_template = init_kfac_state(
            model, kfac, state,
            (batch_np["input_ids"][0], batch_np["token_type_ids"][0],
             batch_np["attention_mask"][0]))
        step_fn = build_kfac_pretrain_step(
            model, tx, kfac, pert_template, schedule=sched,
            max_predictions=4, grad_dtype=grad_dtype, zero1=plan,
            norm_reducer=norm_reducer)
    else:
        step_fn = build_pretrain_step(
            model, tx, schedule=sched, max_predictions=4,
            grad_dtype=grad_dtype, zero1=plan,
            norm_reducer=norm_reducer)

    if inject == "extra_gather":
        from jax.sharding import NamedSharding, PartitionSpec

        base_step = step_fn

        def step_fn(state, batch, rng):  # noqa: F811 — the drill wrapper
            new_state, metrics = base_step(state, batch, rng)
            leaf = jax.tree.leaves(new_state.opt_state.mu)[0]
            rep = jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, PartitionSpec()))
            metrics["injected_gather_probe"] = jnp.sum(rep)
            return new_state, metrics

    if inject == "extra_allreduce":
        base_step = step_fn

        def step_fn(state, batch, rng):  # noqa: F811 — the drill wrapper
            new_state, metrics = base_step(state, batch, rng)
            # a full-tree reduction over a ZeRO-1-sharded mu leaf: GSPMD
            # partial-sums locally then all-reduces the scalar — one
            # unbudgeted all-reduce the exact ceiling must catch
            leaf = jax.tree.leaves(new_state.opt_state.mu)[0]
            metrics["injected_allreduce_probe"] = jnp.sum(
                leaf.astype(jnp.float32))
            return new_state, metrics

    batch = mesh_lib.host_to_device_batch(mesh, batch_np)
    rng = jax.random.PRNGKey(0)
    prog = StepProgram(step_fn, donate_state=(inject != "no_donate"))
    with mesh, mesh_lib.logical_rules():
        lowered = prog.lower(state, batch, rng)
        lowered_text = lowered.as_text()
        compiled = prog.compile()

    args = (state, batch, rng)
    # expected in-shardings + the rule labels that derived them, straight
    # from the logical-axis-rules table (parallel/rules.py via
    # training/pretrain.step_input_expectations) — NOT read back from the
    # built state, so a state construction failed open (the
    # replicated_state drill, or a real PR-2-class bug) still faces the
    # table's expectations
    with mesh_lib.logical_rules():
        abstract = abstract_train_state(jax.random.PRNGKey(0), init_fn, tx)
    expected, exp_rules = step_input_expectations(
        abstract, state, batch, mesh, zero1=spec["zero1"],
        zero1_params=spec["overlap"] and spec["zero1"],
        kfac_shard_axes=kfac.shard_axes if kfac is not None else None)
    if inject == "wrong_axis":
        expected, exp_rules = _inject_wrong_axis(expected, exp_rules, mesh)

    rep = program_report(compiled, args=args, expected=expected,
                         rules=exp_rules, lowered_text=lowered_text,
                         label=name)
    rep["combo"] = dict(spec, inject=inject)
    return rep


def _inject_wrong_axis(expected: list, labels: list, mesh):
    """The sharding_rules gate drill: re-derive ONE leaf's expected spec
    with its mesh axes deliberately swapped (data <-> model), so the
    compiled in-sharding can no longer match and the pass must exit 1
    naming the rule, the leaf path, and both shardings."""
    from jax.sharding import NamedSharding, PartitionSpec

    def swap(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            return tuple(swap(e) for e in entry)
        return {"data": "model", "model": "data"}.get(entry, entry)

    for i, sh in enumerate(expected):
        spec = getattr(sh, "spec", None)
        if spec is None or "data" not in str(spec):
            continue
        expected, labels = list(expected), list(labels)
        expected[i] = NamedSharding(
            mesh, PartitionSpec(*[swap(e) for e in tuple(spec)]))
        labels[i] = f"{labels[i]}+wrong_axis_drill[data<->model]"
        return expected, labels
    raise SystemExit("graphcheck: wrong_axis inject found no leaf with a "
                     "'data'-sharded expectation to swap")


def build_reports(combos, inject: str = "none",
                  progress=None) -> dict:
    out = {}
    for name in combos:
        if name not in COMBOS:
            raise SystemExit(f"graphcheck: unknown combo '{name}' "
                             f"(known: {', '.join(sorted(COMBOS))})")
        if progress:
            progress(f"graphcheck: compiling {name} ...")
        out[name] = build_report(name, COMBOS[name], inject=inject)
    return out


def _meta() -> dict:
    import jax

    return {"platform": jax.devices()[0].platform,
            "num_partitions": N_DEVICES,
            "jax_version": jax.__version__}


# -- CLI ----------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--combos", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--budgets", default=DEFAULT_BUDGETS)
    ap.add_argument("--report", default=None,
                    help="report output path. Default: results/"
                         "graph_report.json for a full clean run; a temp "
                         "path for --combos subsets and --inject drills, "
                         "so partial/broken reports never overwrite the "
                         "checked-in artifact")
    ap.add_argument("--write-budgets", action="store_true",
                    help="re-baseline the budget file from the current "
                         "programs instead of gating")
    ap.add_argument("--validate-budgets", action="store_true",
                    help="jax-free: schema-check the budget file and diff "
                         "an existing report against it")
    ap.add_argument("--report-only", action="store_true",
                    help="build + write the report, skip the gate")
    ap.add_argument("--inject", default="none", choices=INJECTIONS,
                    help="compile a deliberately-broken program (gate "
                         "drill; see module docstring)")
    args = ap.parse_args(argv)

    report_path = args.report
    if report_path is None:
        if args.inject != "none" or args.combos:
            # a drill or subset report is partial/deliberately broken —
            # it must never overwrite the checked-in full-matrix artifact
            # (perfboard indexes it; --validate-budgets diffs it)
            import tempfile

            report_path = os.path.join(
                tempfile.mkdtemp(prefix="graphcheck_"),
                "graph_report.json")
            print(f"graphcheck: subset/drill run — report goes to "
                  f"{report_path}, not {DEFAULT_REPORT}", file=sys.stderr)
        else:
            report_path = DEFAULT_REPORT

    if args.validate_budgets:
        budgets = load_json(args.budgets)
        errors = validate_budgets(budgets)
        for e in errors:
            print(f"graphcheck: budget schema: {e}")
        if errors:
            return 2
        print(f"graphcheck: {args.budgets} schema ok "
              f"({len(budgets['combos'])} combo(s))")
        report_path = args.report or DEFAULT_REPORT
        if os.path.exists(report_path):
            reports = load_json(report_path).get("combos", {})
            n_err = print_findings(diff_reports(reports, budgets))
            return 1 if n_err else 0
        print(f"graphcheck: no report at {report_path} — schema check only")
        return 0

    combos = (args.combos.split(",") if args.combos
              else sorted(COMBOS))
    if args.inject != "none" and not args.combos:
        # injections drill the pretrain step builders; an implicit full
        # matrix must skip the serve/finetune combos (an explicitly-
        # requested one still errors loudly in its builder)
        skipped = [c for c in combos
                   if COMBOS[c].get("kind") in ("serve", "finetune")]
        if skipped:
            print(f"graphcheck: inject drill — skipping serve/finetune "
                  f"combo(s) {', '.join(skipped)}", file=sys.stderr)
            combos = [c for c in combos if c not in skipped]
    reports = build_reports(combos, inject=args.inject,
                            progress=lambda m: print(m, file=sys.stderr))

    os.makedirs(os.path.dirname(os.path.abspath(report_path)) or ".",
                exist_ok=True)
    doc = {"schema_version": 1, **_meta(), "combos": reports}
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"graphcheck: wrote {report_path} ({len(reports)} combo(s))",
          file=sys.stderr)

    if args.write_budgets:
        budgets = budgets_from_reports(reports, _meta())
        with open(args.budgets, "w", encoding="utf-8") as f:
            json.dump(budgets, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"graphcheck: re-baselined {args.budgets} — commit it with "
              "a note on WHY the program changed")
        return 0
    if args.report_only:
        return 0

    if not os.path.exists(args.budgets):
        print(f"graphcheck: no budget file at {args.budgets} — run "
              "graphcheck --write-budgets to create one", file=sys.stderr)
        return 2
    budgets = load_json(args.budgets)
    errors = validate_budgets(budgets)
    if errors:
        for e in errors:
            print(f"graphcheck: budget schema: {e}")
        return 2
    n_err = print_findings(diff_reports(reports, budgets))
    if n_err:
        print(f"graphcheck: FAILED — {n_err} error finding(s); if the "
              "program change is intentional, re-baseline with "
              "--write-budgets and commit the new budgets")
        return 1
    print("graphcheck: all combos within budget")
    return 0


if __name__ == "__main__":
    _force_cpu_devices()
    sys.exit(main())
