#!/usr/bin/env python
"""Stdlib fallback for the ruff gate (scripts/check_graph.sh).

The CI container does not ship ruff and bakes its own toolchain, so the
lint half of the graph gate needs a zero-dependency implementation of the
conservative subset of ruff.toml's rule set that works without scope
analysis:

  F401-ish  unused imports (module-wide usage check, conservative)
  F541      f-string without any placeholder
  F632      `is` / `is not` comparison against a str/int literal
  F821-ish  names that are loaded but bound NOWHERE in the file
            (module-coarse: any binding anywhere in the file counts, so
            scope bugs slip through but typos and deleted helpers are
            caught with near-zero false positives)
  F841-ish  locals assigned but never used (function-coarse: a plain
            `name = ...` / `name: T = ...` / walrus target inside a
            function whose name is LOADED nowhere in that function's
            whole subtree, nested defs included. Underscore-leading
            names, global/nonlocal declarations, augmented assigns,
            tuple unpacking, and assign-then-`del` (Del counts as a
            use, matching pyflakes) are exempt — the scope-free slice
            of the rule; ruff's scope-aware F841 additionally sees
            shadowing and unpacking cases)

ruff.toml additionally selects F811/F823 — scope-aware rules a coarse
checker would false-positive on (this repo lazily re-imports the same
names inside functions by design), so they run only where ruff exists.
`ruff check` passing is strictly stronger than repolint passing.

Files using wildcard imports are skipped for the undefined-name rule
(anything could be bound), and a trailing `# noqa` silences a line.

  python tools/repolint.py [paths...]     # default: the repo's code dirs

Exit 1 when any finding is printed, 0 clean — same contract as
`ruff check`.
"""

from __future__ import annotations

import ast
import builtins
import os
import sys
from typing import List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_TARGETS = ("bert_pytorch_tpu", "tools", "scripts", "tests", "data",
                   "bench.py", "run_pretraining.py", "run_squad.py",
                   "run_ner.py", "__graft_entry__.py")

# names the interpreter/jax inject that a module-coarse pass cannot see
_IMPLICIT = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__path__", "__all__",
    "__version__", "__class__",
}


class _Binder(ast.NodeVisitor):
    """Collect every name BOUND anywhere in the file, any scope."""

    def __init__(self) -> None:
        self.bound: Set[str] = set()
        self.star_import = False

    def _bind_target(self, node: ast.AST) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                self.bound.add(n.id)
            elif isinstance(n, (ast.MatchAs, ast.MatchStar)) \
                    and getattr(n, "name", None):
                self.bound.add(n.name)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.bound.add((a.asname or a.name).split(".")[0])

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            if a.name == "*":
                self.star_import = True
            else:
                self.bound.add(a.asname or a.name)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.bound.add(node.name)
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            self.bound.add(a.arg)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            self.bound.add(a.arg)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._bind_target(t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_NamedExpr(self, node) -> None:
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._bind_target(node.target)
        self.generic_visit(node)

    visit_AsyncFor = visit_For  # type: ignore[assignment]

    def visit_comprehension_target(self, comp: ast.comprehension) -> None:
        self._bind_target(comp.target)

    def visit_ListComp(self, node) -> None:
        for c in node.generators:
            self.visit_comprehension_target(c)
        self.generic_visit(node)

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if item.optional_vars is not None:
                self._bind_target(item.optional_vars)
        self.generic_visit(node)

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.bound.update(node.names)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self.bound.update(node.names)

    def visit_MatchAs(self, node) -> None:
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)


def _noqa_lines(src: str) -> Set[int]:
    return {i for i, line in enumerate(src.splitlines(), 1)
            if "# noqa" in line}


def lint_file(path: str) -> List[Tuple[int, str, str]]:
    """[(line, code, message)] findings for one file."""
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except OSError as e:
        return [(0, "E000", f"unreadable: {e}")]
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, "E999", f"syntax error: {e.msg}")]
    noqa = _noqa_lines(src)
    binder = _Binder()
    binder.visit(tree)
    loads: Set[str] = set()
    findings: List[Tuple[int, str, str]] = []

    # a FormattedValue's format spec (`f"{x:.2f}"`) is itself a nested
    # JoinedStr with no placeholders — never a finding
    spec_ids = {id(n.format_spec) for n in ast.walk(tree)
                if isinstance(n, ast.FormattedValue)
                and n.format_spec is not None}

    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.add(node.id)
        elif isinstance(node, ast.JoinedStr) and id(node) not in spec_ids:
            if not any(isinstance(v, ast.FormattedValue)
                       for v in node.values) and node.lineno not in noqa:
                findings.append((node.lineno, "F541",
                                 "f-string without any placeholders"))
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                # True/False/None are singletons — `is` against them is
                # correct and NOT flagged (matches ruff F632)
                if isinstance(op, (ast.Is, ast.IsNot)) and isinstance(
                        comp, ast.Constant) and isinstance(
                        comp.value, (str, bytes, int, float, tuple)) \
                        and not isinstance(comp.value, bool) \
                        and node.lineno not in noqa:
                    findings.append((node.lineno, "F632",
                                     "`is` comparison with a literal — "
                                     "use =="))

    # F401: imports whose bound name is never loaded anywhere else.
    # __init__.py re-exports on purpose (mirrors ruff.toml's ignore).
    if os.path.basename(path) != "__init__.py":
        for node in ast.walk(tree):
            names = []
            if isinstance(node, ast.Import):
                names = [(a, (a.asname or a.name).split(".")[0])
                         for a in node.names]
            elif isinstance(node, ast.ImportFrom) \
                    and node.module != "__future__":
                names = [(a, a.asname or a.name) for a in node.names
                         if a.name != "*"]
            for alias, bound in names:
                if bound not in loads and bound != "_" \
                        and node.lineno not in noqa:
                    findings.append((node.lineno, "F401",
                                     f"'{bound}' imported but unused"))

    # F841 (function-coarse): plain-assignment locals loaded nowhere in
    # the function subtree. Walked per top-level-reachable function so a
    # name used only in a sibling function still counts as unused.
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_loads: Set[str] = set()
        declared: Set[str] = set()
        for sub in ast.walk(node):
            # `del x` counts as a use (ast.Del ctx), and an augmented
            # assignment implicitly LOADS its target before storing:
            # pyflakes/ruff F841 flag neither assign-then-del nor
            # assign-then-augment, and ruff must stay strictly stronger
            # than this fallback, never weaker
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Load, ast.Del)):
                fn_loads.add(sub.id)
            elif isinstance(sub, ast.AugAssign) \
                    and isinstance(sub.target, ast.Name):
                fn_loads.add(sub.target.id)
            elif isinstance(sub, (ast.Global, ast.Nonlocal)):
                declared.update(sub.names)
        for sub in node.body:  # direct statements only: nested defs get
            # their own walk, and a name assigned in an inner scope is
            # that scope's local, not this one's
            for stmt in ast.walk(sub):
                targets = []
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    targets = [stmt.targets[0]]
                elif isinstance(stmt, ast.AnnAssign) \
                        and stmt.value is not None \
                        and isinstance(stmt.target, ast.Name):
                    targets = [stmt.target]
                elif isinstance(stmt, ast.NamedExpr) \
                        and isinstance(stmt.target, ast.Name):
                    targets = [stmt.target]
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.Lambda,
                                       ast.ClassDef)):
                    break  # don't descend: inner scopes own their locals
                    # (conservative — walk order may skip later nodes of
                    # this statement too; missed findings, never false
                    # positives)
                for t in targets:
                    if t.id.startswith("_") or t.id in fn_loads \
                            or t.id in declared \
                            or t.lineno in noqa:
                        continue
                    findings.append((t.lineno, "F841",
                                     f"local variable '{t.id}' is "
                                     "assigned to but never used"))

    # F821 (module-coarse): loaded names bound nowhere in the file
    if not binder.star_import:
        known = binder.bound | set(dir(builtins)) | _IMPLICIT
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id not in known and node.lineno not in noqa:
                findings.append((node.lineno, "F821",
                                 f"undefined name '{node.id}'"))

    return sorted(set(findings))


def iter_py_files(targets) -> List[str]:
    out = []
    for t in targets:
        path = t if os.path.isabs(t) else os.path.join(REPO, t)
        if os.path.isfile(path) and path.endswith(".py"):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".py"))
    return sorted(set(out))


def main(argv=None) -> int:
    targets = (argv if argv else sys.argv[1:]) or list(DEFAULT_TARGETS)
    n = 0
    files = iter_py_files(targets)
    for path in files:
        for line, code, msg in lint_file(path):
            rel = os.path.relpath(path, REPO)
            print(f"{rel}:{line}: {code} {msg}")
            n += 1
    if n:
        print(f"repolint: {n} finding(s) in {len(files)} files")
        return 1
    print(f"repolint: {len(files)} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
