#!/usr/bin/env python
"""Supervised restart loop: keep a training run alive across deaths.

The in-process survival kit (bert_pytorch_tpu/resilience/) makes a death
cheap — emergency checkpoint on SIGTERM, integrity-verified auto-resume,
watchdog abort on a hang — but something still has to RESTART the
process. On a real cluster that is the orchestrator; on a bare VM / a
preemptible node / a drill it is this script:

    python tools/supervise.py --ckpt_dir out/pretrain_ckpts \\
        -- python run_pretraining.py --output_dir out ...

Behavior (docs/RESILIENCE.md is the runbook):

- reruns the command after a retryable death, with exponential backoff +
  jitter (base doubling to a cap, so a flapping node does not hot-loop);
- halt-code awareness: exit 0 ends supervision; EXIT_NONFINITE_HALT (71)
  and EXIT_WATCHDOG_DEVICE_HANG (72) are NOT retried (a deterministic
  blowup replays identically; a wedged device wants a drain, not the
  same host) — the code is propagated so the layer above sees it;
  signals (128+sig / negative returncodes) and other nonzero codes are
  retried — including EXIT_SLO_BREACH (76), run_pretraining's
  --slo_action=halt verdict on a SUSTAINED page-severity train SLO
  breach (stuck input pipeline, straggler host): restart-worthy, a
  fresh process usually clears it, and the restart budget + crash-loop
  detector still bound a breach that restarts can't fix;
- crash-loop detection: each restart must MOVE the checkpoint
  (`latest_step_on_disk(--ckpt_dir)` strictly greater than before the
  attempt) — after --crash_loop_tolerance consecutive no-progress
  deaths, exit EXIT_CRASH_LOOP (74) instead of burning the budget on a
  run that dies before its first save;
- restart budget: --max_restarts total, then EXIT_RESTART_BUDGET (75);
- lineage: the child env carries BERT_SUPERVISOR_RESTARTS (attempt
  index, read by telemetry into /healthz + bert_supervisor_restarts,
  and by the chaos drills to fire only in the first incarnation);
- SIGTERM/SIGINT at the SUPERVISOR mean "stop supervising": the signal
  is forwarded to the child (triggering its emergency checkpoint) and
  the loop exits with the child's code instead of restarting — operator
  stop and child preemption are different events.

jax-free by design: the supervisor must outlive whatever broke the
child's interpreter.
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import subprocess
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.resilience import (  # noqa: E402
    EXIT_CRASH_LOOP, EXIT_RESTART_BUDGET, NO_RETRY_EXIT_CODES)
from bert_pytorch_tpu.resilience.manifest import (  # noqa: E402
    latest_step_on_disk)


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--ckpt_dir", required=True, type=str,
                   help="checkpoint directory the run saves into (e.g. "
                        "<output_dir>/pretrain_ckpts) — the crash-loop "
                        "detector's progress probe reads it jax-free")
    p.add_argument("--max_restarts", type=int, default=16,
                   help="total restart budget before exit 75")
    p.add_argument("--crash_loop_tolerance", type=int, default=3,
                   help="consecutive deaths without checkpoint progress "
                        "before exit 74 (a run dying before its first "
                        "save is a bug, not weather)")
    p.add_argument("--backoff_base", type=float, default=2.0,
                   help="first retry delay in seconds; doubles per "
                        "consecutive failure")
    p.add_argument("--backoff_max", type=float, default=120.0,
                   help="backoff ceiling in seconds")
    p.add_argument("--backoff_jitter", type=float, default=0.25,
                   help="uniform jitter fraction added to each delay "
                        "(de-synchronizes a fleet restarting after one "
                        "fabric event)")
    p.add_argument("--no_retry_codes", type=str,
                   default=",".join(str(c) for c in NO_RETRY_EXIT_CODES),
                   help="comma-separated exit codes never retried "
                        "(default: 71 NonFiniteHalt, 72 watchdog device "
                        "hang)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="the training command, after `--`")
    args = p.parse_args(argv)
    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given (pass it after `--`)")
    args.command = cmd
    return args


def _log(msg: str) -> None:
    print(f"supervise: {msg}", file=sys.stderr, flush=True)


def supervise(cmd, ckpt_dir: str, max_restarts: int = 16,
              crash_loop_tolerance: int = 3, backoff_base: float = 2.0,
              backoff_max: float = 120.0, backoff_jitter: float = 0.25,
              no_retry_codes=NO_RETRY_EXIT_CODES,
              env=None, sleep=None, log=_log) -> int:
    """The restart loop; returns the process-tree's final exit code.
    Importable (tests and the drill gate drive it in-process). `sleep`
    defaults to an interruptible Event.wait so an operator signal cuts
    the backoff short instead of waiting out up to backoff_max."""
    no_retry = {int(c) for c in no_retry_codes}
    restarts = 0
    no_progress = 0
    stopping = [None]  # signal the SUPERVISOR received, if any
    child_holder = [None]
    stop_event = threading.Event()
    if sleep is None:
        sleep = stop_event.wait

    def _on_signal(signum, frame):
        stopping[0] = signum
        stop_event.set()  # cut any in-flight backoff sleep short
        child = child_holder[0]
        if child is not None and child.poll() is None:
            log(f"forwarding {signal.Signals(signum).name} to child "
                f"pid {child.pid} (emergency checkpoint path)")
            try:
                child.send_signal(signum)
            except OSError:
                pass

    old_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old_handlers[sig] = signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass

    last_rc = 0
    try:
        while True:
            if stopping[0] is not None:
                # operator stop arrived between attempts (e.g. during the
                # backoff sleep): do NOT burn another full attempt
                log(f"supervisor received "
                    f"{signal.Signals(stopping[0]).name} between "
                    "attempts — stopping supervision")
                if last_rc == 0:
                    return 0
                return last_rc if last_rc > 0 else 128 + (-last_rc)
            step_before = latest_step_on_disk(ckpt_dir)
            child_env = dict(os.environ if env is None else env)
            child_env["BERT_SUPERVISOR_RESTARTS"] = str(restarts)
            child_env["BERT_SUPERVISED"] = "1"
            log(f"attempt {restarts}: launching (checkpoint step on "
                f"disk: {step_before}): {' '.join(cmd)}")
            child = subprocess.Popen(cmd, env=child_env)
            child_holder[0] = child
            rc = child.wait()
            child_holder[0] = None
            last_rc = rc

            if rc == 0:
                log("run completed cleanly (exit 0) — supervision done")
                return 0
            name = _describe_exit(rc)
            if stopping[0] is not None:
                log(f"supervisor received "
                    f"{signal.Signals(stopping[0]).name}; child exited "
                    f"{name} — stopping supervision (operator stop, not "
                    "a preemption)")
                return rc if rc > 0 else 128 + (-rc)
            if rc in no_retry:
                log(f"child exited {name} — in the no-retry set "
                    f"{sorted(no_retry)}; halting supervision "
                    "(restarting would replay the same failure)")
                return rc

            step_after = latest_step_on_disk(ckpt_dir)
            progressed = (step_before is None and step_after is not None) \
                or (step_before is not None and step_after is not None
                    and step_after > step_before)
            if progressed:
                no_progress = 0
            else:
                no_progress += 1
                if no_progress >= crash_loop_tolerance:
                    log(f"CRASH LOOP: {no_progress} consecutive deaths "
                        f"without checkpoint progress (stuck at step "
                        f"{step_after}) — exit {EXIT_CRASH_LOOP}")
                    return EXIT_CRASH_LOOP

            restarts += 1
            if restarts > max_restarts:
                log(f"restart budget exhausted ({max_restarts}) — exit "
                    f"{EXIT_RESTART_BUDGET}")
                return EXIT_RESTART_BUDGET
            # exponential in the NO-PROGRESS streak: a death after real
            # progress restarts at the base delay (preemption weather),
            # repeated early deaths back off hard
            delay = min(backoff_base * (2.0 ** no_progress), backoff_max)
            delay *= 1.0 + backoff_jitter * random.random()
            log(f"child exited {name}; restart {restarts}/{max_restarts} "
                f"in {delay:.1f}s (checkpoint progress: "
                f"{step_before} -> {step_after})")
            sleep(delay)
    finally:
        for sig, old in old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass


def _describe_exit(rc: int) -> str:
    if rc < 0:
        try:
            return f"{rc} (killed by {signal.Signals(-rc).name})"
        except ValueError:
            return str(rc)
    if rc > 128:
        try:
            return f"{rc} (128+{signal.Signals(rc - 128).name})"
        except ValueError:
            return str(rc)
    names = {71: "NONFINITE_HALT", 72: "WATCHDOG_DEVICE_HANG",
             73: "WATCHDOG_INPUT_STARVED", 76: "SLO_BREACH"}
    return f"{rc} ({names[rc]})" if rc in names else str(rc)


def main(argv=None) -> int:
    args = parse_arguments(argv)
    codes = [int(c) for c in str(args.no_retry_codes).split(",")
             if str(c).strip()]
    return supervise(
        args.command, args.ckpt_dir,
        max_restarts=args.max_restarts,
        crash_loop_tolerance=args.crash_loop_tolerance,
        backoff_base=args.backoff_base, backoff_max=args.backoff_max,
        backoff_jitter=args.backoff_jitter, no_retry_codes=codes)


if __name__ == "__main__":
    sys.exit(main())
