#!/usr/bin/env python
"""Resilience drills: prove the survival kit end-to-end, bit for bit.

Two drills (scripts/check_resilience.sh runs both as a CI gate;
tests/test_resilience.py drives the same functions in tier-1):

1. `sigkill` (the headline): a pretraining run is SIGKILLed mid-interval
   (--chaos sigkill_at_step — the un-catchable death), tools/supervise.py
   restarts it, auto-resume restores the last checkpoint, and the
   resumed run's FINAL PARAMS and METRIC STREAM are bit-identical to an
   uninterrupted run's. Runs on the offline (sharded-HDF5) and streaming
   (tokenize-on-the-fly) data planes, --packing on — the full
   deterministic-resume surface (sampler cursor, packer carry-over,
   stream cursor, per-step fold_in dropout keys) under the worst death.

2. `corrupt`: the run dies right after its newest checkpoint is
   byte-flipped (--chaos corrupt_newest_ckpt); the supervised restart
   must QUARANTINE the corrupt step (renamed `<step>.corrupt`, warning
   naming the failed item), fall back to the next-newest, and STILL
   converge to the bit-identical final state.

"Bit-identical metric stream" means: collect every per-step `train`
record from both runs' jsonl (the killed run's stream spans two process
lifetimes and may log an overlap region twice — once pre-kill, once
replayed after resume); for every step, all logged `step_loss` values
must agree exactly, and the two runs must cover the same steps with the
same values. Timestamps/averages legitimately differ; the training
trajectory may not.

Subprocess sessions force the CPU backend and an 8-device host platform
so the drill exercises the real sharded path deterministically anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEED = 42
MAX_STEPS = 5
CKPT_EVERY = 2
# Mid-interval, and far enough past the step-2 boundary that the ASYNC
# step-2 save has committed before the kill lands (a kill racing the
# very first commit leaves no checkpoint — the restart then legitimately
# starts fresh, which is survival but not the resume path this drill
# must prove).
KILL_AT = 5

# As small as the model can be while still exercising every resume
# surface (packing, NSP, MLM head, checkpointed cursors): the drill's
# cost is dominated by per-session XLA compiles on a one-core CI box,
# and compile time scales with graph size
MODEL_CFG = {
    "vocab_size": 64, "hidden_size": 16, "num_hidden_layers": 1,
    "num_attention_heads": 2, "intermediate_size": 32,
    "max_position_embeddings": 64, "next_sentence": True,
    "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
    "tokenizer": "wordpiece", "fused_ops": False, "attention_impl": "xla",
}

_WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot",
          "golf", "hotel", "india", "juliet", "kilo", "lima", "mike",
          "november", "oscar", "papa"]
_SPECIALS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]


def _log(msg: str) -> None:
    print(f"resilience_drill: {msg}", file=sys.stderr, flush=True)


# -- fixtures ----------------------------------------------------------------


def write_model_config(workdir: str) -> str:
    path = os.path.join(workdir, "model_config.json")
    if not os.path.exists(path):
        with open(path, "w", encoding="utf-8") as f:
            json.dump(MODEL_CFG, f)
    return path


def write_offline_shards(workdir: str, n: int = 48, seq: int = 32) -> str:
    """Varied-length HDF5 shards (the corpus shape --packing exists for);
    same schema as pipeline/encode.py output."""
    import h5py
    import numpy as np

    data = os.path.join(workdir, "data")
    if os.path.isdir(data):
        return data
    os.makedirs(data)
    for s in range(2):
        rng = np.random.RandomState(s)
        # token ids stay below MODEL_CFG["vocab_size"]
        ids = rng.randint(5, 60, (n, seq)).astype(np.int32)
        ids[:, 0] = 1  # [CLS]
        specials = np.zeros((n, 3), np.int32)
        for i in range(n):
            last = rng.randint(7, seq - 1)
            sep1 = rng.randint(2, last - 2)
            ids[i, sep1] = 2
            ids[i, last] = 2
            ids[i, last + 1:] = 0
            specials[i] = [0, sep1, last]
        labels = rng.randint(0, 2, (n,)).astype(np.int8)
        with h5py.File(os.path.join(data, f"shard_{s}.hdf5"), "w") as f:
            f.create_dataset("input_ids", data=ids)
            f.create_dataset("special_token_positions", data=specials)
            f.create_dataset("next_sentence_labels", data=labels)
    return data


def write_stream_corpus(workdir: str, n_docs: int = 80) -> Dict[str, str]:
    """Raw-text corpus + vocab for the streaming plane (data/streaming.py
    FileSource contract: blank-line-delimited documents)."""
    import numpy as np

    corpus = os.path.join(workdir, "corpus")
    vocab = os.path.join(workdir, "vocab.txt")
    if not os.path.isdir(corpus):
        os.makedirs(corpus)
        rng = np.random.RandomState(0)
        for fi in range(2):
            lines = []
            for _ in range(n_docs // 2):
                for _ in range(rng.randint(2, 6)):
                    lines.append(" ".join(
                        rng.choice(_WORDS, rng.randint(3, 12))))
                lines.append("")
            with open(os.path.join(corpus, f"c{fi}.txt"), "w",
                      encoding="utf-8") as fh:
                fh.write("\n".join(lines))
    if not os.path.exists(vocab):
        with open(vocab, "w", encoding="utf-8") as f:
            f.write("\n".join(_SPECIALS + _WORDS) + "\n")
    return {"corpus": corpus, "vocab": vocab}


def drill_argv(plane: str, workdir: str, out_dir: str,
               extra: Optional[List[str]] = None) -> List[str]:
    """run_pretraining argv for one drill session (packing on, tiny
    model, checkpoint every CKPT_EVERY steps)."""
    cfg = write_model_config(workdir)
    argv = ["--model_config_file", cfg, "--output_dir", out_dir,
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--packing", "--packing_max_segments", "4",
            "--learning_rate", "1e-3", "--global_batch_size", "16",
            "--local_batch_size", "2", "--max_predictions_per_seq", "5",
            "--max_steps", str(MAX_STEPS), "--seed", str(SEED),
            "--num_steps_per_checkpoint", str(CKPT_EVERY),
            "--log_freq", "1", "--log_prefix", "drill",
            # startup dominates these 15s sessions: skip the ~4s
            # torch.utils.tensorboard (tensorflow/keras) import
            "--tensorboard", "off"]
    if plane == "offline":
        argv += ["--input_dir", write_offline_shards(workdir),
                 "--mask_token_index", "3"]
    elif plane == "stream":
        fx = write_stream_corpus(workdir)
        argv += ["--stream_dir", fx["corpus"], "--stream_vocab",
                 fx["vocab"], "--stream_seq_len", "32"]
    else:
        raise ValueError(f"plane {plane!r}: want offline|stream")
    return argv + list(extra or [])


def subprocess_env() -> Dict[str, str]:
    """Child env: CPU backend, 8-device host platform (matching
    tests/conftest.py so every session compiles the identical sharded
    program), repo importable. NOTE: deliberately no persistent
    compilation cache — a SIGKILLed session can tear the cache entry it
    was writing and the restarted session segfaults loading it (the
    drill found its own torn-write failure in that layer)."""
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", "")).strip()
    flags = re.sub(r"--xla_backend_optimization_level=\d+", "",
                   flags).strip()
    # optimization level 0: the drill's correctness claims are about
    # BIT-IDENTITY between sessions compiled with the SAME flags, so the
    # cheapest compile wins — 2.6s vs 7.6s of XLA time per session, and
    # every session (reference included) runs under this env so the
    # comparisons never cross program families
    env["XLA_FLAGS"] = \
        (flags + " --xla_force_host_platform_device_count=8"
         " --xla_backend_optimization_level=0").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def run_session(argv: List[str], env: Optional[Dict[str, str]] = None
                ) -> int:
    """One run_pretraining subprocess session; returns its exit code."""
    cmd = [sys.executable, os.path.join(REPO, "run_pretraining.py"),
           "--force_cpu"] + argv
    return subprocess.call(cmd, env=env or subprocess_env(), cwd=REPO)


# -- comparators -------------------------------------------------------------


def _ensure_cpu8() -> None:
    """The comparator restores 8-device-sharded checkpoints, so the
    PARENT needs the same faked 8-device CPU platform the sessions used
    (tests/conftest.py recipe). No-op when already configured (pytest)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def final_params(out_dir: str) -> Dict[str, "object"]:
    """{leaf path: numpy array} of the params in the NEWEST checkpoint."""
    _ensure_cpu8()
    import jax

    from bert_pytorch_tpu.training.checkpoint import CheckpointManager

    mgr = CheckpointManager(os.path.join(out_dir, "pretrain_ckpts"))
    try:
        state, step = mgr.restore_raw()
    finally:
        mgr.close()
    params = state["params"] if isinstance(state, dict) else state.params
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): v
            for path, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    flat["__step__"] = step
    return flat


def compare_params(a: Dict, b: Dict) -> List[str]:
    import numpy as np

    errors = []
    if set(a) != set(b):
        errors.append(f"param trees differ: only-in-a="
                      f"{sorted(set(a) - set(b))[:3]} only-in-b="
                      f"{sorted(set(b) - set(a))[:3]}")
        return errors
    for k in sorted(a):
        if k == "__step__":
            if a[k] != b[k]:
                errors.append(f"final checkpoint step differs: "
                              f"{a[k]} vs {b[k]}")
            continue
        av, bv = np.asarray(a[k]), np.asarray(b[k])
        if av.shape != bv.shape or av.dtype != bv.dtype:
            errors.append(f"{k}: shape/dtype {av.shape}/{av.dtype} vs "
                          f"{bv.shape}/{bv.dtype}")
        elif av.tobytes() != bv.tobytes():
            d = np.max(np.abs(av.astype("float64")
                              - bv.astype("float64")))
            errors.append(f"{k}: NOT bit-identical (max abs diff {d:g})")
    return errors


def metric_stream(out_dir: str, prefix: str = "drill"
                  ) -> Dict[int, float]:
    """{step: step_loss} from the jsonl train records; raises on
    self-contradiction (the same step logged twice with different
    values — a killed+resumed run logs the replayed overlap twice, and
    those MUST agree bit-for-bit)."""
    path = os.path.join(out_dir, f"{prefix}.jsonl")
    out: Dict[int, float] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("tag") != "train" or "step_loss" not in rec:
                continue
            step, loss = int(rec["step"]), rec["step_loss"]
            if step in out and out[step] != loss:
                raise AssertionError(
                    f"{path}: step {step} logged twice with DIFFERENT "
                    f"step_loss ({out[step]!r} vs {loss!r}) — the "
                    "replayed overlap is not bit-identical")
            out[step] = loss
    return out


def compare_streams(a: Dict[int, float], b: Dict[int, float]
                    ) -> List[str]:
    errors = []
    if set(a) != set(b):
        errors.append(f"metric streams cover different steps: "
                      f"{sorted(set(a) ^ set(b))}")
    for step in sorted(set(a) & set(b)):
        if a[step] != b[step]:
            errors.append(f"step {step}: step_loss {a[step]!r} vs "
                          f"{b[step]!r} — not bit-identical")
    return errors


# -- drills ------------------------------------------------------------------


def run_reference(plane: str, workdir: str) -> str:
    """The uninterrupted control run — a subprocess under the SAME env
    as every other drill session (subprocess_env), so the compiled
    program, and therefore every bit of the result, is comparable."""
    out = os.path.join(workdir, f"ref_{plane}")
    rc = run_session(drill_argv(plane, workdir, out))
    if rc != 0:
        raise RuntimeError(f"reference {plane} run failed rc={rc}")
    return out


def run_supervised_chaos(plane: str, workdir: str, chaos: str,
                         chaos_step: int, tag: str) -> str:
    """One chaos session + supervised restart(s) to completion."""
    from tools.supervise import supervise

    out = os.path.join(workdir, f"{tag}_{plane}")
    argv = drill_argv(plane, workdir, out,
                      extra=["--chaos", chaos,
                             "--chaos_step", str(chaos_step)])
    cmd = [sys.executable, os.path.join(REPO, "run_pretraining.py"),
           "--force_cpu"] + argv
    rc = supervise(cmd, os.path.join(out, "pretrain_ckpts"),
                   max_restarts=3, crash_loop_tolerance=3,
                   backoff_base=0.1, backoff_max=0.5,
                   env=subprocess_env(), log=_log)
    if rc != 0:
        raise RuntimeError(
            f"supervised {chaos} {plane} drill did not converge (rc={rc})")
    return out


def verify_bit_identical(ref_out: str, drill_out: str) -> List[str]:
    errors = compare_params(final_params(ref_out), final_params(drill_out))
    errors += compare_streams(metric_stream(ref_out),
                              metric_stream(drill_out))
    return errors


def drill_sigkill(plane: str, workdir: str,
                  ref_out: Optional[str] = None) -> List[str]:
    """Headline drill on one data plane; returns verification errors.
    `ref_out` reuses an existing uninterrupted control run (same
    drill_argv config + subprocess_env) instead of running a fresh one —
    the tier-1 test shares one reference between this drill and the
    SIGTERM e2e."""
    if ref_out is None:
        _log(f"[sigkill/{plane}] reference run ...")
        ref = run_reference(plane, workdir)
    else:
        ref = ref_out
    _log(f"[sigkill/{plane}] SIGKILL at step {KILL_AT} + supervise ...")
    out = run_supervised_chaos(plane, workdir, "sigkill_at_step",
                               KILL_AT, "sigkill")
    errors = verify_bit_identical(ref, out)
    # the drill must actually have died once: the supervisor's lineage
    # env shows up in the resumed session's auto-resume log line
    log = open(os.path.join(out, "drill.txt"), encoding="utf-8").read()
    if "auto-resumed from step" not in log:
        errors.append("drill log never auto-resumed — the kill or the "
                      "restart did not happen")
    return errors


def drill_corrupt(plane: str, workdir: str) -> List[str]:
    """Corrupt-newest drill: die right after corrupting the freshest
    checkpoint; the restart must quarantine + fall back + still converge
    bit-identically."""
    _log(f"[corrupt/{plane}] reference run ...")
    ref = run_reference(plane, workdir)
    _log(f"[corrupt/{plane}] corrupt newest ckpt at step {CKPT_EVERY * 2} "
         "+ SIGKILL + supervise ...")
    out = run_supervised_chaos(plane, workdir, "corrupt_newest_ckpt",
                               CKPT_EVERY * 2, "corrupt")
    errors = verify_bit_identical(ref, out)
    log = open(os.path.join(out, "drill.txt"), encoding="utf-8").read()
    if "is CORRUPT" not in log or "Quarantined" not in log:
        errors.append("drill log shows no quarantine warning")
    ckpts = os.path.join(out, "pretrain_ckpts")
    if not any(name.endswith(".corrupt") for name in os.listdir(ckpts)):
        errors.append(f"no quarantined *.corrupt dir under {ckpts}")
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--drill", default="all",
                   choices=["sigkill", "corrupt", "all"])
    p.add_argument("--plane", default="both",
                   choices=["offline", "stream", "both"])
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    args = p.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="resilience_drill_")
    os.makedirs(workdir, exist_ok=True)
    planes = (["offline", "stream"] if args.plane == "both"
              else [args.plane])
    failures = 0
    for plane in planes:
        if args.drill in ("sigkill", "all"):
            errors = drill_sigkill(plane, workdir)
            _log(f"[sigkill/{plane}] "
                 + ("PASS — SIGKILLed+supervised run is bit-identical "
                    "to the uninterrupted run" if not errors
                    else "FAIL:\n  " + "\n  ".join(errors)))
            failures += bool(errors)
        if args.drill in ("corrupt", "all"):
            errors = drill_corrupt(plane, workdir)
            _log(f"[corrupt/{plane}] "
                 + ("PASS — corrupt newest quarantined, fallback resumed "
                    "bit-identically" if not errors
                    else "FAIL:\n  " + "\n  ".join(errors)))
            failures += bool(errors)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
