#!/usr/bin/env python
"""Open-loop load generator + SERVE artifact assembly for the inference server.

Open-loop means arrival times are scheduled from the target rate alone
(request j fires at t0 + j/rate) regardless of how fast responses come
back — the discipline that actually measures tail latency under load; a
closed loop self-throttles exactly when the server saturates and reports
flattering percentiles. Jax-free (a load generator that imports the
serving stack is measuring itself).

Three modes:

  python tools/loadtest.py --url http://127.0.0.1:8000 --label packed \
      --rates 20,50 --duration 3 --out /tmp/packed.json
      # fire a mixed squad/ner burst at each swept rate; per rate record
      # p50/p95/p99 latency, achieved req/s, real_tokens/s, and the batch
      # occupancy over the window (delta of the server's cumulative
      # real/slot token counters, scraped from /metrics).

  python tools/loadtest.py --assemble SERVE_r01.json packed.json padded.json
      # merge mode files into the cross-mode SERVE artifact perfboard
      # indexes and scripts/check_perf.sh gates.

  python tools/loadtest.py --validate SERVE_r01.json
      # jax-free schema check (scripts/check_serve.sh gates on it); exit
      # 2 on violations.

Exit codes (run mode): 0 with >=1 2xx response, 1 when every request
failed (the server is down or shedding everything), 2 unusable input.
"""

from __future__ import annotations

import argparse
import http.client
import json
import math
import os
import sys
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.telemetry.registry import parse_prometheus  # noqa: E402

SERVE_SCHEMA_VERSION = 1
RATE_REQUIRED_KEYS = ("n", "n_2xx", "n_err", "duration_s", "p50_ms",
                      "p95_ms", "p99_ms", "req_per_sec",
                      "real_tokens_per_sec", "batch_occupancy")

# tiny deterministic word pool for synthetic payloads — the server's
# tokenizer maps unknown words to [UNK]; token COUNTS (what batching and
# throughput accounting see) are what matters here, not semantics
_WORDS = ("the cat sat on the mat a dog did run in the park who what "
          "where when how why fast slow red blue green bert serves "
          "packed rows").split()


def _payload(task: str, i: int, squad_long_every: int = 0,
             long_index: Optional[int] = None) -> Dict[str, Any]:
    """Deterministic request #i for any registered task, lengths varied
    so packing has something to pack (contexts 8-56 words, sentences
    4-36). Every task in tasks/registry.py must have a generator here —
    tests/test_task_registry.py pins the coverage.

    squad_long_every=N injects one LONG squad context (~440 words, the
    largest serving bucket) every Nth request — the heavy-tailed service
    mix the replica scale-out sweep needs: a realistic fleet serves rare
    long documents alongside dominant short traffic, and the tail of the
    SHORT requests stuck behind a long wave is exactly what work stealing
    exists to fix. 0 (default) keeps the legacy all-short mix.

    `long_index` decouples long placement from content: run_rate passes
    the LEG-LOCAL request index so every rate leg carries the same long
    fraction at the same phase (longs land at leg index N/2, 3N/2, ...).
    A global index here would scatter 0..5 longs per leg depending on
    where the cumulative offset fell — measured to make the per-rate p99
    curve non-monotone and the saturation rate meaningless."""
    pick = lambda k, n: " ".join(_WORDS[(k * 7 + j) % len(_WORDS)]
                                 for j in range(n))
    if task == "squad":
        if squad_long_every:
            li = i if long_index is None else long_index
            if li % squad_long_every == squad_long_every // 2:
                return {"question": f"who did thing {i % 13} ?",
                        "context": pick(i, 440) + " ."}
            # heavy-tailed mode needs the tail CONTROLLED: clamp short
            # contexts under the 64-token bucket, or every ~49th
            # "short" (56 words ~ 65+ tokens) silently rides the
            # largest bucket and the injected long fraction is a lie
            return {"question": f"who did thing {i % 13} ?",
                    "context": pick(i, 8 + (i * 11) % 28) + " ."}
        return {"question": f"who did thing {i % 13} ?",
                "context": pick(i, 8 + (i * 11) % 49) + " ."}
    if task == "classify":
        out = {"text": pick(i, 4 + (i * 5) % 29)}
        if i % 3 == 0:
            out["text_pair"] = pick(i + 1, 3 + (i * 7) % 17)
        return out
    if task == "choice":
        return {"question": pick(i, 3 + i % 7),
                "choices": [pick(i + c, 2 + (i + c) % 9)
                            for c in range(2 + i % 3)]}
    if task == "embed":
        if i % 4 == 0:  # batch-embed request
            return {"texts": [pick(i + t, 3 + (i + t) % 13)
                              for t in range(2 + i % 3)]}
        return {"text": pick(i, 4 + (i * 5) % 29)}
    return {"tokens": pick(i, 4 + (i * 5) % 33).split()}


def parse_task_mix(spec: str) -> List[str]:
    """'squad:2,ner:1' -> ['squad', 'squad', 'ner'] — the weighted
    round-robin task cycle a mixed-traffic sweep alternates through.
    Bare names get weight 1; 'all' expands to every registered task
    (the only path that imports the registry — plain --tasks stays
    jax-free)."""
    tasks: List[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        name = name.strip()
        w = int(weight) if weight.strip() else 1
        if w < 1:
            raise SystemExit(f"loadtest: --task_mix weight {w} < 1 "
                             f"({part!r})")
        if name == "all":
            from bert_pytorch_tpu.tasks.registry import all_tasks

            names = list(all_tasks())
        else:
            names = [name]
        for n in names:
            tasks.extend([n] * w)
    if not tasks:
        raise SystemExit(f"loadtest: empty --task_mix {spec!r}")
    return tasks


def _get(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


class _Client:
    """One persistent HTTP/1.1 connection (keep-alive). A per-request
    TCP connect + server-side thread spawn costs more than a tiny-model
    forward — without reuse the load test measures connection churn, not
    the serving stack."""

    def __init__(self, base_url: str, timeout: float):
        u = urllib.parse.urlsplit(base_url)
        self._host, self._port = u.hostname, u.port or 80
        self._timeout = timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def post(self, path: str, body: Dict[str, Any]
             ) -> Tuple[int, Dict[str, Any], Optional[str]]:
        """(status, body, X-Trace-Id header) — the trace id is what turns
        a slow response in this load test into a /v1/traces lookup."""
        data = json.dumps(body).encode("utf-8")
        for attempt in (0, 1):  # one silent reconnect on a dropped conn
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout)
            try:
                self._conn.request(
                    "POST", path, body=data,
                    headers={"Content-Type": "application/json"})
                r = self._conn.getresponse()
                payload = r.read()
                trace_id = r.getheader("X-Trace-Id")
                try:
                    return (r.status,
                            json.loads(payload.decode("utf-8")), trace_id)
                except ValueError:
                    return r.status, {}, trace_id
            except Exception as e:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
                if attempt:
                    return 0, {"error": f"{type(e).__name__}: {e}"}, None
        return 0, {}, None

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return float("nan")
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))]


def _scrape_serve(url: str) -> Optional[Dict[str, float]]:
    """Cumulative serving counters from /metrics, labels summed: real/slot
    tokens (occupancy) plus device-seconds and the device-hour price
    (cost-per-token). Missing series sum to 0.0 — an older server without
    the cost counters still yields occupancy."""
    try:
        parsed = parse_prometheus(_get(url + "/metrics"))
    except Exception:
        return None
    price = parsed.get("bert_serve_cost_per_device_hour", {})
    return {
        "real": sum(parsed.get("bert_serve_real_tokens_total", {}).values()),
        "slot": sum(parsed.get("bert_serve_slot_tokens_total", {}).values()),
        "device_seconds": sum(
            parsed.get("bert_serve_device_seconds_total", {}).values()),
        "cost_per_device_hour": next(iter(price.values()), 0.0),
    }


def run_rate(url: str, rate: float, duration: float, tasks: List[str],
             timeout: float, offset: int = 0,
             squad_long_every: int = 0,
             trace_log: Optional[List[Tuple[float, str]]] = None
             ) -> Dict[str, Any]:
    """One open-loop sweep at `rate` req/s for `duration` seconds.
    `trace_log` (when given) accumulates (latency_ms, X-Trace-Id) pairs
    for every 2xx across legs — the slowest entries are what
    --save_traces fetches back from /v1/traces after the sweep."""
    n = max(1, int(round(rate * duration)))
    lat_ms: List[float] = []
    statuses: List[int] = []
    real_tokens = [0.0]
    lock = threading.Lock()
    before = _scrape_serve(url)
    t0 = time.perf_counter()

    def fire(client: _Client, j: int) -> None:
        target = t0 + j / rate
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        task = tasks[j % len(tasks)]
        t_send = time.perf_counter()
        code, body, trace_id = client.post(
            f"/v1/{task}",
            _payload(task, offset + j, squad_long_every=squad_long_every,
                     long_index=j))
        ms = (time.perf_counter() - t_send) * 1e3
        with lock:
            statuses.append(code)
            if 200 <= code < 300:
                lat_ms.append(ms)
                real_tokens[0] += float(body.get("real_tokens", 0))
                if trace_log is not None and trace_id:
                    trace_log.append((ms, trace_id))

    # capped worker pool, arrivals interleaved across workers: worker w
    # owns requests w, w+W, w+2W, ... at their open-loop times, all on
    # ONE keep-alive connection. A slow response delays only that
    # worker's next arrival (1/W of the stream) — close enough to
    # open-loop at W=128 without a thread+connection per request.
    n_workers = min(128, n)

    def worker(w: int) -> None:
        client = _Client(url, timeout)
        try:
            for j in range(w, n, n_workers):
                fire(client, j)
        finally:
            client.close()

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(n_workers)]
    for t in threads:
        t.start()
    # worst case per worker: its whole request chain times out one by one
    # — budget for that, or stats below would be computed from a moving
    # snapshot while stragglers still append
    per_worker = -(-n // n_workers)  # ceil
    join_deadline = time.monotonic() + duration + per_worker * timeout + 60
    for t in threads:
        t.join(max(0.0, join_deadline - time.monotonic()))
    straggling = sum(1 for t in threads if t.is_alive())
    elapsed = max(time.perf_counter() - t0, 1e-9)
    after = _scrape_serve(url)
    with lock:  # freeze the shared lists even if stragglers survive
        lat_ms = list(lat_ms)
        statuses = list(statuses)
        total_real_tokens = real_tokens[0]

    occupancy = 0.0
    cost_fields: Dict[str, float] = {}
    if before is not None and after is not None:
        d_real = after["real"] - before["real"]
        d_slot = after["slot"] - before["slot"]
        occupancy = round(d_real / d_slot, 6) if d_slot > 0 else 0.0
        d_dev = after["device_seconds"] - before["device_seconds"]
        price = after["cost_per_device_hour"]
        if d_dev > 0:
            cost_fields["device_seconds"] = round(d_dev, 6)
            if d_real > 0 and price > 0:
                cost_fields["cost_per_1k_tokens"] = round(
                    d_dev / 3600.0 * price / (d_real / 1000.0), 9)
    n_2xx = sum(1 for s in statuses if 200 <= s < 300)
    by_code: Dict[str, int] = {}
    for s in statuses:
        by_code[str(s)] = by_code.get(str(s), 0) + 1

    def pct(q: float) -> Optional[float]:
        # a sweep with zero 2xx has no latency distribution: null (not 0)
        # so the artifact FAILS validation instead of flattering the gate
        v = _percentile(lat_ms, q)
        return None if math.isnan(v) else round(v, 3)

    out = {
        "rate_target": rate,
        "n": n,
        "n_2xx": n_2xx,
        "n_err": len(statuses) - n_2xx,
        "by_code": dict(sorted(by_code.items())),
        "duration_s": round(elapsed, 3),
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
        "p99_ms": pct(0.99),
        "req_per_sec": round(n_2xx / elapsed, 3),
        "real_tokens_per_sec": round(total_real_tokens / elapsed, 1),
        "batch_occupancy": occupancy,
    }
    out.update(cost_fields)
    if straggling:
        out["straggling_workers"] = straggling
    return out


def parse_rate_sweep(spec: str) -> List[float]:
    """'START:FACTOR:MAX' -> geometric rate ramp [START, START*FACTOR,
    ...] up to and including the first rate >= MAX — the open-loop
    saturation curve grid (`--rate_sweep`)."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise SystemExit(f"loadtest: --rate_sweep wants START:FACTOR:MAX, "
                         f"got {spec!r}")
    start, factor, stop = (float(p) for p in parts)
    if start <= 0 or factor <= 1 or stop < start:
        raise SystemExit(f"loadtest: bad --rate_sweep {spec!r} (need "
                         "START>0, FACTOR>1, MAX>=START)")
    rates, r = [], start
    while True:
        rates.append(round(r, 6))
        if r >= stop:
            return rates
        r *= factor


def saturation_from_rates(rates: Dict[str, Any],
                          p99_bound: Optional[float]) -> Dict[str, Any]:
    """Mode-level saturation: the best ACHIEVED req/s among swept rates
    whose p99 stayed under the bound (no bound: among all rates with any
    2xx). 'At equal p99 bound' is the whole point — raw peak req/s past
    the latency knee flatters a saturated server that is busy timing
    everyone out."""
    best = None
    for rec in rates.values():
        p99 = rec.get("p99_ms")
        if not rec.get("n_2xx") or not isinstance(p99, (int, float)):
            continue
        if p99_bound is not None and p99 > p99_bound:
            continue
        if best is None or rec["req_per_sec"] > best["req_per_sec"]:
            best = rec
    out = {
        "p99_bound_ms": p99_bound,
        "req_per_sec": best["req_per_sec"] if best else 0.0,
        "at_rate": best["rate_target"] if best else None,
        "p99_ms": best["p99_ms"] if best else None,
    }
    # cost at the saturation point — the "cost per 1k tokens at equal
    # p99" number perfboard gates (lower-better)
    if best is not None:
        for k in ("cost_per_1k_tokens", "device_seconds"):
            if k in best:
                out[k] = best[k]
    return out


def _collect_traces(url: str, label: str,
                    trace_log: List[Tuple[float, str]],
                    out_dir: str, top_n: int = 16) -> Dict[str, Any]:
    """Fetch the slowest client-observed request traces from /v1/traces
    and save them beside the SERVE artifact. Targeted fetch first (the
    X-Trace-Ids of our slowest 2xx responses); falls back to the server's
    full flight-recorder snapshot when those ids already rotated out of
    the ring. Returns the mode-record fields (file path + per-phase
    summary); empty dict when the server has no tracing."""
    fields: Dict[str, Any] = {}
    slowest = sorted(trace_log, reverse=True)[:top_n]
    # one response can carry several comma-joined ids (batch embed)
    ids = [tid for _, joined in slowest
           for tid in joined.split(",") if tid]
    doc = None
    if ids:
        try:
            doc = json.loads(_get(
                url + "/v1/traces?id=" + ",".join(ids[:64])))
        except Exception:
            doc = None
    if not (doc and doc.get("traceEvents")):
        try:
            doc = json.loads(_get(url + "/v1/traces"))
        except Exception:
            return fields
    if not doc.get("traceEvents"):
        return fields
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"traces_{label}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
        f.write("\n")
    fields["trace_file"] = path
    if ids:
        fields["slowest_trace_ids"] = ids[:top_n]
    try:
        from bert_pytorch_tpu.telemetry.trace import \
            summarize_request_events

        summary = summarize_request_events(doc["traceEvents"])
        fields["request_trace_summary"] = summary
        p99 = summary.get("p99") or {}
        if p99.get("dominant_phase"):
            where = f" on {p99['replica']}" if p99.get("replica") else ""
            print(f"loadtest: [{label}] p99 is "
                  f"{p99['dominant_share']:.0%} "
                  f"{p99['dominant_phase']}{where} "
                  f"({summary['n_traces']} trace(s) saved -> {path})",
                  file=sys.stderr)
    except Exception as e:  # summary is best-effort; the file is saved
        print(f"loadtest: [{label}] trace summary failed: {e}",
              file=sys.stderr)
    return fields


def run_mode(url: str, label: str, rates: List[float], duration: float,
             tasks: List[str], timeout: float,
             meta: Optional[Dict[str, Any]] = None,
             p99_bound: Optional[float] = None,
             squad_long_every: int = 0,
             save_traces: Optional[str] = None) -> Dict[str, Any]:
    out: Dict[str, Any] = {"schema_version": SERVE_SCHEMA_VERSION,
                           "kind": "serve_mode", "label": label,
                           "url": url, "tasks": tasks,
                           "time_unix": round(time.time(), 3), "rates": {}}
    if meta:
        out["meta"] = dict(meta)
    trace_log: Optional[List[Tuple[float, str]]] = \
        [] if save_traces else None
    offset = 0
    for rate in rates:
        print(f"loadtest: [{label}] rate {rate:g} req/s x {duration:g}s ...",
              file=sys.stderr)
        rec = run_rate(url, rate, duration, tasks, timeout, offset=offset,
                       squad_long_every=squad_long_every,
                       trace_log=trace_log)
        offset += rec["n"]
        out["rates"][f"{rate:g}"] = rec
        print(f"loadtest: [{label}] rate {rate:g}: {rec['n_2xx']}/{rec['n']} "
              f"2xx, p50 {rec['p50_ms']}ms p99 {rec['p99_ms']}ms, "
              f"{rec['req_per_sec']} req/s, occupancy "
              f"{rec['batch_occupancy']}", file=sys.stderr)
    out["saturation"] = saturation_from_rates(out["rates"], p99_bound)
    sat = out["saturation"]
    print(f"loadtest: [{label}] saturation {sat['req_per_sec']:g} req/s "
          f"(p99 bound {p99_bound}, at target rate {sat['at_rate']})",
          file=sys.stderr)
    if save_traces and trace_log is not None:
        out.update(_collect_traces(url, label, trace_log, save_traces))
    try:
        out["healthz"] = json.loads(_get(url + "/healthz"))
    except Exception:
        pass
    return out


# -- artifact assembly + validation (jax-free, perfboard-compatible) ----------


def _sat_per_chip(mode: Dict[str, Any]) -> Optional[float]:
    """Saturation req/s per chip — the distillation headline unit."""
    sat = mode.get("saturation") or {}
    rps = sat.get("req_per_sec")
    if not isinstance(rps, (int, float)) or not rps:
        return None
    n_chips = (mode.get("meta") or {}).get("n_chips")
    return rps / (n_chips if isinstance(n_chips, (int, float))
                  and n_chips > 0 else 1)


def assemble(mode_paths: List[str], kind: str = "serve",
             accuracies: Optional[Dict[str, float]] = None
             ) -> Dict[str, Any]:
    modes: Dict[str, Any] = {}
    newest = 0.0
    for path in mode_paths:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        label = doc.get("label") or os.path.splitext(
            os.path.basename(path))[0]
        modes[label] = {"rates": doc.get("rates", {}),
                        "tasks": doc.get("tasks"),
                        "url": doc.get("url")}
        for extra in ("meta", "saturation", "request_trace_summary",
                      "trace_file", "slowest_trace_ids"):
            if doc.get(extra) is not None:
                modes[label][extra] = doc[extra]
        newest = max(newest, float(doc.get("time_unix") or 0))
    # replica scale-out ratio: each multi-replica mode vs the
    # single-replica mode of the SAME dtype (the PR-17 acceptance
    # number, gated by perfboard as scaleout higher-better)
    singles = {str(m.get("meta", {}).get("dtype", "")): m
               for m in modes.values()
               if m.get("meta", {}).get("replicas") == 1
               and m.get("saturation", {}).get("req_per_sec")}
    for mode in modes.values():
        meta = mode.get("meta", {})
        base = singles.get(str(meta.get("dtype", "")))
        if (base is not None and base is not mode
                and isinstance(meta.get("replicas"), int)
                and meta["replicas"] > 1
                and mode.get("saturation", {}).get("req_per_sec")):
            mode["saturation"]["vs_single_replica"] = round(
                mode["saturation"]["req_per_sec"]
                / base["saturation"]["req_per_sec"], 3)
    out = {"schema_version": SERVE_SCHEMA_VERSION, "kind": kind,
           "time_unix": newest or round(time.time(), 3), "modes": modes}
    if kind != "distill":
        return out
    # distill artifact: modes are teacher/student serving legs keyed by
    # meta.model_tag (--model_tag — no filename conventions); each leg
    # gains its task accuracy, its delta vs the teacher (the accuracy-
    # floor gate input), and its per-chip saturation ratio vs the
    # teacher leg of the same dtype (f32 teacher as fallback)
    acc = dict(accuracies or {})
    out["accuracies"] = acc
    teacher_acc = acc.get("teacher")
    teachers = {str(m.get("meta", {}).get("dtype", "")): m
                for m in modes.values()
                if str(m.get("meta", {}).get("model_tag", "")) == "teacher"
                and m.get("saturation", {}).get("req_per_sec")}
    for mode in modes.values():
        meta = mode.get("meta", {})
        tag = meta.get("model_tag")
        if tag is None:
            continue
        tag = str(tag)
        if tag in acc:
            mode["accuracy"] = acc[tag]
            if teacher_acc is not None:
                mode["accuracy_delta"] = round(teacher_acc - acc[tag], 6)
        if tag == "teacher":
            continue
        base = (teachers.get(str(meta.get("dtype", "")))
                or next(iter(teachers.values()), None))
        mine = _sat_per_chip(mode)
        theirs = _sat_per_chip(base) if base is not None else None
        if mine and theirs:
            mode["saturation"]["vs_teacher_per_chip"] = round(
                mine / theirs, 3)
    return out


def validate_serve(doc: Any) -> List[str]:
    """Schema errors of a SERVE artifact (empty list = valid)."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return ["artifact is not a JSON object"]
    if doc.get("schema_version") != SERVE_SCHEMA_VERSION:
        errors.append(f"schema_version {doc.get('schema_version')!r} != "
                      f"{SERVE_SCHEMA_VERSION}")
    modes = doc.get("modes")
    if not isinstance(modes, dict) or not modes:
        return errors + ["'modes' missing or empty"]
    for label, mode in sorted(modes.items()):
        rates = mode.get("rates") if isinstance(mode, dict) else None
        if not isinstance(rates, dict) or not rates:
            errors.append(f"mode '{label}': no 'rates'")
            continue
        for rate, rec in sorted(rates.items()):
            if not isinstance(rec, dict):
                errors.append(f"mode '{label}' rate {rate}: not an object")
                continue
            for k in RATE_REQUIRED_KEYS:
                v = rec.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or (isinstance(v, float) and math.isnan(v)):
                    errors.append(f"mode '{label}' rate {rate}: field "
                                  f"'{k}' missing or non-numeric ({v!r})")
        sat = mode.get("saturation") if isinstance(mode, dict) else None
        if sat is not None:
            v = sat.get("req_per_sec") if isinstance(sat, dict) else None
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errors.append(f"mode '{label}': saturation.req_per_sec "
                              f"missing or non-numeric ({v!r})")
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--url", default=None, help="server base URL")
    ap.add_argument("--label", default="packed",
                    help="mode label recorded in the output (packed/padded)")
    ap.add_argument("--rates", default="10,30",
                    help="comma-separated request rates (req/s) to sweep")
    ap.add_argument("--rate_sweep", default=None, metavar="START:FACTOR:MAX",
                    help="geometric saturation ramp (overrides --rates): "
                         "sweep START, START*FACTOR, ... through MAX and "
                         "record the mode's saturation req/s at the p99 "
                         "bound")
    ap.add_argument("--p99_bound", type=float, default=None,
                    help="latency SLO for the saturation number: only "
                         "rates with p99_ms <= this count (no bound: any "
                         "rate with >=1 2xx)")
    ap.add_argument("--meta", action="append", default=None,
                    metavar="KEY=VALUE",
                    help="mode metadata recorded in the artifact "
                         "(replicas=2, dtype=f32, n_chips=2, ...); "
                         "repeatable — perfboard renders replica/dtype "
                         "columns from it")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds per rate sweep")
    ap.add_argument("--tasks", default="squad,ner",
                    help="comma-separated tasks to alternate between")
    ap.add_argument("--task_mix", default=None,
                    help="weighted mixed-traffic spec, e.g. "
                         "'squad:2,ner:1,classify:1' or 'all' / 'all:1' "
                         "(every registered task, equal weight); "
                         "overrides --tasks")
    ap.add_argument("--squad_long_every", type=int, default=0,
                    help="inject one ~440-word squad context every Nth "
                         "request (0 = off): the heavy-tailed service "
                         "mix the replica scale-out sweep measures")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="per-request client timeout (s)")
    ap.add_argument("--save_traces", default=None, metavar="DIR",
                    help="after the sweep, fetch the slowest-request "
                         "span timelines from /v1/traces (ids captured "
                         "from X-Trace-Id response headers) and save "
                         "traces_{label}.json under DIR; the per-phase "
                         "summary is embedded in the mode record")
    ap.add_argument("--model_tag", default=None,
                    help="which model this leg serves (teacher, "
                         "student_6l_768, ...); recorded as "
                         "meta.model_tag so perfboard can index "
                         "teacher/student legs from one artifact")
    ap.add_argument("--out", default=None, help="mode JSON output path")
    ap.add_argument("--assemble", nargs="+", default=None,
                    metavar=("OUT", "MODE_JSON"),
                    help="merge mode files into a SERVE artifact: "
                         "OUT IN1 [IN2 ...]")
    ap.add_argument("--kind", choices=["serve", "distill"],
                    default="serve",
                    help="artifact kind for --assemble: 'distill' adds "
                         "per-leg accuracy, accuracy_delta vs the "
                         "teacher leg, and saturation."
                         "vs_teacher_per_chip")
    ap.add_argument("--accuracy", action="append", default=None,
                    metavar="TAG=VAL",
                    help="task accuracy for a model_tag (teacher=0.92 "
                         "student_6l_768=0.91); repeatable, used by "
                         "--assemble --kind distill")
    ap.add_argument("--require_healthy", action="store_true",
                    help="check /healthz before sending traffic and fail "
                         "fast (exit 3) when the target's SLO status is "
                         "'failing' — a bench leg against a failing "
                         "server measures the outage, not the server")
    ap.add_argument("--validate", default=None, metavar="SERVE_JSON",
                    help="schema-check a SERVE artifact and exit")
    args = ap.parse_args(argv)

    if args.validate:
        try:
            with open(args.validate, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"loadtest: unreadable {args.validate}: {e}")
            return 2
        errors = validate_serve(doc)
        for e in errors:
            print(f"loadtest: schema: {e}")
        if errors:
            return 2
        n_rates = sum(len(m.get("rates", {}))
                      for m in doc["modes"].values())
        print(f"loadtest: {args.validate} schema ok "
              f"({len(doc['modes'])} mode(s), {n_rates} rate sweep(s))")
        return 0

    if args.assemble:
        if len(args.assemble) < 2:
            print("loadtest: --assemble needs OUT and >=1 mode file")
            return 2
        out_path, mode_paths = args.assemble[0], args.assemble[1:]
        accuracies = {}
        for entry in args.accuracy or []:
            k, sep, v = entry.partition("=")
            try:
                accuracies[k] = float(v)
            except ValueError:
                sep = ""
            if not sep or not k:
                print(f"loadtest: --accuracy wants TAG=VAL, got {entry!r}")
                return 2
        doc = assemble(mode_paths, kind=args.kind, accuracies=accuracies)
        errors = validate_serve(doc)
        for e in errors:
            print(f"loadtest: schema: {e}")
        if errors:
            return 2
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
            f.write("\n")
        print(f"loadtest: wrote {out_path} ({', '.join(sorted(doc['modes']))})")
        return 0

    if not args.url:
        print("loadtest: --url required (or --assemble/--validate)")
        return 2
    if args.require_healthy:
        hz_url = args.url.rstrip("/") + "/healthz"
        try:
            hz = json.loads(_get(hz_url, timeout=args.timeout))
        except Exception as e:
            print(f"loadtest: --require_healthy: {hz_url} unreachable "
                  f"({e})", file=sys.stderr)
            return 3
        status = hz.get("status", "ok")
        if status == "failing":
            firing = (hz.get("slo") or {}).get("firing", [])
            print(f"loadtest: --require_healthy: target reports "
                  f"status=failing (firing: {', '.join(firing) or '?'}) "
                  "— refusing to send traffic", file=sys.stderr)
            return 3
        if status != "ok":
            print(f"loadtest: warning: target status={status} "
                  "(proceeding)", file=sys.stderr)
    if args.rate_sweep:
        rates = parse_rate_sweep(args.rate_sweep)
    else:
        rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if args.task_mix:
        tasks = parse_task_mix(args.task_mix)
    else:
        tasks = [t.strip() for t in args.tasks.split(",") if t.strip()]
    meta = {}
    if args.model_tag:
        meta["model_tag"] = args.model_tag
    for entry in args.meta or []:
        k, sep, v = entry.partition("=")
        if not sep or not k:
            print(f"loadtest: --meta wants KEY=VALUE, got {entry!r}")
            return 2
        try:
            meta[k] = int(v)
        except ValueError:
            try:
                meta[k] = float(v)
            except ValueError:
                meta[k] = v
    doc = run_mode(args.url.rstrip("/"), args.label, rates, args.duration,
                   tasks, args.timeout, meta=meta or None,
                   p99_bound=args.p99_bound,
                   squad_long_every=args.squad_long_every,
                   save_traces=args.save_traces)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
            f.write("\n")
        print(f"loadtest: wrote {args.out}", file=sys.stderr)
    else:
        json.dump(doc, sys.stdout, indent=1, sort_keys=True,
                  allow_nan=False)
        print()
    total_2xx = sum(r["n_2xx"] for r in doc["rates"].values())
    if total_2xx == 0:
        print("loadtest: FAILED — zero 2xx responses", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
